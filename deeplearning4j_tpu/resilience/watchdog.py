"""Stall watchdog: detect a wedged training step and dump a crash
report while the evidence is still alive.

A hung collective, a dead-locked host callback, or a loader stuck on a
dead filesystem all present the same way: the step simply never ends,
no exception, no log line — the most expensive failure mode there is,
because nothing pages anyone. The watchdog is a monitor THREAD with two
inputs:

- a **per-trainer heartbeat**: every trainer step calls
  `ACTIVE.beat("multilayer@<id>")` — keyed per INSTANCE, so two
  concurrent fits of the same class can't mask each other's stall or
  retire each other's beats (one dict store behind the usual
  `if _watchdog.ACTIVE is not None:` pointer compare — zero cost
  disarmed);
- PR 4's **flight recorder** (`monitoring/steps.py`) for the step-time
  history that goes into the report.

When the OLDEST live trainer's heartbeat is older than `stall_timeout`
(env `DL4J_STALL_TIMEOUT`, default 300 s) while the watchdog is ARMED
(between `arm()` / `disarm()` — an idle process after fit() returns is
not a stall), it:

1. writes `dl4j-stall-report-<ts>-<pid>.txt`: per-trainer heartbeat
   ages, every Python thread's stack (`sys._current_frames` — this is
   how you see the wedged collective), the open monitoring spans of
   every thread, the flight-recorder tail, and the last device-memory
   reading;
2. bumps `dl4j.watchdog.stalls` / `dl4j.watchdog.dumps` and keeps
   `dl4j.watchdog.beat_age_seconds` fresh;
3. optionally aborts: `abort=True` interrupts the main thread
   (KeyboardInterrupt — lets `finally:` blocks flush checkpoints),
   `abort=<callable>` runs yours (e.g. `lambda: os._exit(134)` for a
   supervisor-managed restart). CAVEAT: interrupt_main only fires when
   the main thread next runs Python bytecode — if the MAIN thread is
   the one wedged inside a native call (the hung collective itself),
   abort=True cannot reach it; the report still gets written, but only
   `abort=<callable>` with `os._exit` actually ends the process then.

The trip LATCHES until the next heartbeat, so one stall produces one
report, and a recovered step re-arms detection automatically.

Oldest-live, not newest: with two concurrent trainers beating one
watchdog, a live trainer's fresh beats must not mask a wedged one's
silence. A fit that ENDS retires its name (`retire()`, wired into the
model/wrapper fit epilogues) so a finished trainer cannot age into a
false trip; functional step loops (`ShardedTrainer.fit_batch` driven
directly) have no fit scope — disarm the watchdog when such a loop
finishes inside an armed window.

    wd = StallWatchdog(stall_timeout=120).start()
    wd.arm()
    try:
        net.fit(iterator, epochs=10)
    finally:
        wd.disarm(); wd.stop()

`FaultTolerantTrainer(..., watchdog=wd)` does the arm/disarm around its
own fit. State surfaces at `GET /health`.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import events as _events

__all__ = ["ACTIVE", "StallWatchdog", "clear_watchdog", "default_timeout",
           "write_debug_report"]

#: THE switch the trainer heartbeat hooks check (faults.py pattern).
ACTIVE = None


def default_timeout():
    try:
        return float(os.environ.get("DL4J_STALL_TIMEOUT", "300"))
    except ValueError:
        return 300.0


def _peer_table_lines():
    """Peer-table section for crash/stall reports: the multi-host
    coordinator's view of every process (step, heartbeat age, preempt
    flag). Lazy + best-effort — single-process runs (no coordinator
    installed) get one explanatory line, and a broken coordination
    service must never stop a report from being written."""
    lines = ["Peer table (multi-host):"]
    try:
        # read through sys.modules, never import: if coordination was
        # never loaded, no coordinator can be installed — and a stall
        # report must not pay (or deadlock on) a whole-package import
        # inside a process that is by definition wedged
        mod = sys.modules.get("deeplearning4j_tpu.parallel.coordination")
        coord = getattr(mod, "ACTIVE", None) if mod is not None else None
    except Exception:  # noqa: BLE001 — report must always be writable
        coord = None
    if coord is None:
        lines.append("  (single process — no coordinator installed)")
        return lines
    try:
        table = coord.peer_table()
    except Exception as e:  # noqa: BLE001
        lines.append(f"  (peer table unavailable: {e})")
        return lines
    if not table:
        lines.append("  (no peer heartbeats observed yet)")
    for pid, info in sorted(table.items()):
        lines.append(f"  process {pid}: {info}")
    return lines


def write_debug_report(headline, dump_dir=None, prefix="dl4j-stall-report",
                       extra_sections=None, count_dump=True):
    """Write the full forensics report both the stall watchdog and the
    multi-host peer monitor use: the headline, any caller sections
    (heartbeat tables, peer autopsies), open monitoring spans, every
    Python thread's stack, the flight-recorder tail, the last device
    memory reading, and the multi-host peer table. Returns the report
    path. `extra_sections` is a list of line-lists inserted after the
    headline. Every caller (stall watchdog, peer monitor, crash dumps
    via util/crash_reporting) shares the journal-tail section below,
    and a machine-readable post-mortem bundle rides alongside the text
    report when monitoring is enabled."""
    ts = time.strftime("%Y%m%d-%H%M%S")
    directory = dump_dir or os.getcwd()
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{prefix}-{ts}-{os.getpid()}.txt")
    lines = [f"deeplearning4j_tpu {prefix} ({ts})", "=" * 60, ""]
    if isinstance(headline, str):
        lines.append(headline)
    else:
        lines.extend(headline)
    lines.append("")
    for section in (extra_sections or ()):
        lines.extend(section)
        lines.append("")
    lines.extend(_events.event_tail_lines())
    lines.append("")
    lines.extend(_peer_table_lines())
    lines.append("")
    lines.append("Open monitoring spans by thread:")
    spans = _mon.get_tracer().open_spans()
    if spans:
        for tid, stack in sorted(spans.items()):
            lines.append(f"  thread {tid}: {' > '.join(stack)}")
    else:
        lines.append("  (none recorded — monitoring disabled or "
                     "between spans)")
    lines.append("")
    lines.append("Python thread stacks:")
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        if tid == threading.get_ident():
            continue               # the reporting thread is not evidence
        lines.append(f"  -- thread {tid} ({names.get(tid, '?')}) --")
        for ln in traceback.format_stack(frame):
            lines.extend("  " + s for s in ln.rstrip().splitlines())
    lines.append("")
    lines.append("Step-time flight recorder:")
    lines.extend(_mon.step_recorder().crash_lines())
    lines.append("")
    mem = _mon.memory.last_sample()
    lines.append("Last device memory reading:")
    if mem:
        for k, v in sorted(mem.items()):
            lines.append(f"  {k}: {v}")
    else:
        lines.append("  (none — memory telemetry not sampling)")
    if _mon.enabled():
        bundle_path = _events.write_bundle(
            dump_dir=directory, headline=f"{prefix}: see {path}")
        lines.append("")
        lines.append(f"Post-mortem bundle: {bundle_path or '(failed)'}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    if count_dump and _mon.enabled():
        _mon.get_registry().counter(
            _mon.WATCHDOG_DUMPS,
            help="stall crash-report files written").inc()
    return path


class StallWatchdog:
    def __init__(self, stall_timeout=None, poll_interval=None, abort=False,
                 on_stall=None, dump_dir=None, clock=time.monotonic):
        self.stall_timeout = (default_timeout() if stall_timeout is None
                              else float(stall_timeout))
        if self.stall_timeout <= 0:
            raise ValueError("stall_timeout must be > 0")
        self.poll_interval = (min(1.0, self.stall_timeout / 4.0)
                              if poll_interval is None
                              else float(poll_interval))
        self.abort = abort
        self.on_stall = on_stall
        self.dump_dir = dump_dir
        self._clock = clock
        self._beats = {}           # trainer name -> monotonic timestamp
        self._retired = {}         # name -> retire timestamp (fit ended)
        self._prev_active = None   # watchdog shadowed by install()
        self._armed = 0            # arm() nesting depth (see arm())
        self._armed_at = None
        self.stalled = False       # latched until the next beat
        self.stall_count = 0
        self.last_report_path = None
        self._thread = None
        self._stop = threading.Event()

    # -- the hot hook ----------------------------------------------------
    def beat(self, name="trainer"):
        """One step heartbeat: a dict store (atomic under the GIL — no
        lock on the hot path). A latched stall clears once no live
        trainer is stale anymore — the step that finally completed IS
        the recovery signal, but another trainer's beats must not
        unlatch a stall it didn't cause (that would re-trip a report
        every poll while the wedged one stays silent)."""
        # beat BEFORE un-retiring: the reverse order opens a window in
        # which the monitor sees neither entry and anchors a fresh fit's
        # first step on the stale armed_at — a false trip
        self._beats[name] = self._clock()
        self._retired.pop(name, None)
        if self.stalled:
            age = self.beat_age()
            if age is None or age <= self.stall_timeout:
                self.stalled = False
                if _mon.enabled():
                    _events.emit(
                        "resilience", _events.WATCHDOG_RECOVERED,
                        attrs={"trainer": name},
                        correlation_id="watchdog-%x" % id(self))

    def retire(self, name="trainer"):
        """A trainer's fit completed: its heartbeat stops being stall
        evidence (detection watches the OLDEST live trainer, so a name
        that legitimately finished must not age into a false trip).
        Reaching fit's end is itself proof of liveness — the retire
        timestamp anchors detection while no trainer is live."""
        self._beats.pop(name, None)
        self._retired[name] = self._clock()

    # -- lifecycle -------------------------------------------------------
    def install(self):
        """Install as ACTIVE, remembering the watchdog this one shadows
        so stop()/uninstall() restores it — a second watchdog (e.g. a
        serving MemoryMonitor-style scope inside a training run's) must
        not strip the outer one from the beats that follow, leaving an
        armed watchdog starved of heartbeats until it false-trips."""
        global ACTIVE
        if ACTIVE is not self:
            self._prev_active = ACTIVE
            ACTIVE = self
        return self

    def uninstall(self):
        """Undo this watchdog's install(): restore the watchdog it
        shadowed (None when there was none). A no-op unless this one is
        currently ACTIVE."""
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = self._prev_active
            self._prev_active = None
        return self

    def start(self):
        """Install as the ACTIVE heartbeat sink and spawn the monitor
        thread. Idempotent."""
        self.install()
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dl4j-stall-watchdog")
            self._thread.start()
        return self

    def stop(self):
        self.uninstall()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def arm(self):
        """Begin watching: arming counts as an implicit heartbeat, so a
        run that wedges before its FIRST step still trips. arm/disarm
        NEST (a count, not a flag): two overlapping FaultTolerantTrainer
        fits sharing one watchdog each arm around their own scope, and
        the first to finish must not switch detection off under the
        second — only the outermost arm opens a fresh window (clearing
        heartbeats from before it: stale names from an earlier run must
        not read as wedged trainers in this one), and only the last
        disarm ends it."""
        if self._armed == 0:
            self._beats.clear()
            self._retired.clear()
            self._armed_at = self._clock()
            self.stalled = False
        self._armed += 1
        return self

    def disarm(self):
        self._armed = max(0, self._armed - 1)
        return self

    @property
    def armed(self):
        return self._armed > 0

    # -- detection -------------------------------------------------------
    def beat_age(self):
        """Seconds since the OLDEST live trainer's last heartbeat; None
        when disarmed. Oldest, not newest: with two concurrent trainers
        beating one watchdog, the live one's fresh beats must not mask
        the wedged one's silence — a finished fit retires its name so it
        cannot age into a false trip. With no live trainer, the anchor
        is the latest sign of life (arm() or the newest retirement —
        between a driver's per-batch fits the dict is briefly empty)."""
        if not self._armed:
            return None
        # list() first: trainer threads insert new keys concurrently and
        # a bare .values() iteration would raise "dictionary changed
        # size" mid-scan
        oldest = min(list(self._beats.values()), default=None)
        if oldest is None:
            anchor = max([self._armed_at]
                         + list(self._retired.values()))
        else:
            anchor = oldest
        return self._clock() - anchor

    def check_now(self):
        """One synchronous detection pass (what the monitor thread runs
        per poll; exposed so tests drive it without real sleeps).
        Returns True when this call TRIPPED a new stall."""
        age = self.beat_age()
        if _mon.enabled() and age is not None:
            _mon.get_registry().gauge(
                _mon.WATCHDOG_BEAT_AGE_SECONDS,
                help="seconds since the oldest live trainer's "
                     "heartbeat") \
                .set(age)
        if age is None or age <= self.stall_timeout or self.stalled:
            return False
        self.stalled = True        # latched until the next beat
        self.stall_count += 1
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.WATCHDOG_STALLS,
                help="training steps that exceeded the stall "
                     "timeout").inc()
            _events.emit(
                "resilience", _events.WATCHDOG_STALL,
                attrs={"beat_age_s": round(age, 3),
                       "timeout_s": self.stall_timeout},
                correlation_id="watchdog-%x" % id(self))
        try:
            self.last_report_path = self._write_report(age)
        except Exception:  # noqa: BLE001 — the report must never kill us
            self.last_report_path = None
        if self.on_stall is not None:
            try:
                self.on_stall(self)
            except Exception:  # noqa: BLE001
                pass
        if self.abort:
            if callable(self.abort):
                self.abort()
            else:
                import _thread
                _thread.interrupt_main()
        return True

    def _run(self):
        while not self._stop.wait(self.poll_interval):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 — monitor must stay alive
                pass

    # -- the report ------------------------------------------------------
    def _write_report(self, age):
        now = self._clock()
        beats = ["Heartbeats:"]
        if self._beats:
            for name, t in sorted(list(self._beats.items())):
                beats.append(f"  {name}: {now - t:.1f} s ago")
        else:
            beats.append("  (no step ever completed since arm())")
        return write_debug_report(
            f"stall: no trainer heartbeat for {age:.1f} s "
            f"(timeout {self.stall_timeout:.1f} s)",
            dump_dir=self.dump_dir, extra_sections=[beats])

    # -- introspection (GET /health) -------------------------------------
    def snapshot(self):
        age = self.beat_age()
        return {
            "status": "stalled" if self.stalled else (
                "watching" if self._armed else "disarmed"),
            "armed": self.armed,
            "stalled": self.stalled,
            "stall_count": self.stall_count,
            "stall_timeout_s": self.stall_timeout,
            "beat_age_s": age,
            # live AND retired: a trainer whose fit just finished is
            # still part of the window's story (retired ones are not
            # stall evidence, but /health readers want to see them)
            "heartbeats": {k: round(self._clock() - v, 3)
                           for k, v in (list(self._retired.items())
                                        + list(self._beats.items()))},
            "live": sorted(self._beats),
            "last_report": self.last_report_path,
        }


def clear_watchdog():
    """Force-reset the global switch, ignoring any shadow chain — test
    teardown and emergency use only; running code pairs install() with
    uninstall()/stop()."""
    global ACTIVE
    ACTIVE = None
