"""Retry + circuit-breaker policies (≡ the reference's
SharedTrainingMaster transport retry / mesh rejoin behavior, distilled
into two reusable host-side primitives).

`RetryPolicy` — exponential backoff with deterministic seeded jitter,
attempt and wall-clock deadline budgets, and a retryable-error
classifier: transient device/runtime errors retry, device OOM never
does (retrying an OOM-ed dispatch just OOMs again and hides the real
fix — see `util/crash_reporting.py` for the mitigations we print
instead).

`CircuitBreaker` — classic closed/open/half-open. After
`failure_threshold` consecutive failures the breaker OPENS and sheds
calls with `CircuitOpenError` for `cooldown` seconds; the first call
after cooldown runs as a HALF-OPEN probe — success closes the breaker,
failure re-opens it for another cooldown.

Every retry, trip, and shed is counted through `monitoring/`
(`dl4j.resilience.*`), one flag check and no allocation when monitoring
is disabled.
"""
from __future__ import annotations

import random
import re
import threading
import time

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.resilience.errors import (CircuitOpenError,
                                                  DistributedInitError,
                                                  FatalTrainingError,
                                                  InferenceTimeoutError,
                                                  PeerLostError,
                                                  PreemptionSignal,
                                                  RetryExhaustedError,
                                                  TransientError)
from deeplearning4j_tpu.util.crash_reporting import CrashReportingUtil

__all__ = ["RetryPolicy", "CircuitBreaker", "default_classifier"]

#: transient device/runtime signatures (XLA/PJRT status codes and the
#: usual transport blips); word-ish bounded like crash_reporting's OOM
#: regex so ordinary ValueErrors don't read as retryable
_TRANSIENT_RE = re.compile(
    r"UNAVAILABLE|DEADLINE_EXCEEDED|ABORTED|CANCELLED|INTERNAL"
    r"|[Cc]onnection (?:reset|refused|closed)|[Bb]roken pipe"
    r"|[Tt]emporarily unavailable|[Pp]reempt|[Ss]ocket closed"
    r"|[Tt]imed? ?out")


def default_classifier(exc):
    """True when `exc` is safe to retry.

    Order matters: the explicit TYPES win over message heuristics (a
    `FatalTrainingError("preempted")` must not retry just because its
    message pattern-matches transient), and OOM wins over everything
    (a RESOURCE_EXHAUSTED that also says "try again" must NOT retry —
    reusing `CrashReportingUtil.is_oom` keeps the two subsystems'
    definitions of OOM identical); only then the transient message
    signatures."""
    if CrashReportingUtil.is_oom(exc):
        return False
    if isinstance(exc, (FatalTrainingError, RetryExhaustedError,
                        InferenceTimeoutError, DistributedInitError,
                        PeerLostError, PreemptionSignal)):
        # typed non-retryables: a deadline that fully elapsed, an
        # already-exhausted retry, a dead peer, or a preemption notice
        # must not be retried just because the class name / message
        # ("...TimeoutError", "preempted") pattern-matches transient
        # below — the bootstrap retries connects itself; a lost peer
        # needs a worker restart, not an in-process retry; a preemption
        # means EXIT, retrying it defeats the drain
        return False
    if isinstance(exc, TransientError):
        return True
    msg = f"{type(exc).__name__}: {exc}"
    return _TRANSIENT_RE.search(msg) is not None


class RetryPolicy:
    """Exponential backoff + jitter with attempt/deadline budgets.

    Deterministic: jitter comes from a seeded `random.Random`, so a
    seeded fault plan plus a seeded policy replays the exact same retry
    schedule run after run (the property the resume tests rely on).
    `sleep`/`clock` are injectable for tests.
    """

    def __init__(self, max_attempts=5, initial_backoff=0.05,
                 max_backoff=5.0, multiplier=2.0, jitter=0.1,
                 deadline=None, classifier=None, seed=0,
                 sleep=time.sleep, clock=time.monotonic):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff = float(initial_backoff)
        self.max_backoff = float(max_backoff)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)
        self.classifier = classifier or default_classifier
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock

    def backoff(self, attempt):
        """Backoff before retry number `attempt` (1-based), jittered
        multiplicatively in [1-jitter, 1+jitter]."""
        base = min(self.max_backoff,
                   self.initial_backoff * self.multiplier ** (attempt - 1))
        if self.jitter:
            base *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, base)

    def call(self, fn, *args, on_retry=None, label="call", **kwargs):
        """Run `fn(*args, **kwargs)`, retrying classified-transient
        failures with backoff. Non-retryable errors propagate untouched
        on the spot; exhausted budgets raise `RetryExhaustedError` with
        the last failure as `__cause__`. `on_retry(attempt, exc)` runs
        before each re-attempt (the trainer restores its pre-attempt rng
        snapshot there)."""
        start = self._clock()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — classified below
                if not self.classifier(e):
                    raise
                if attempt >= self.max_attempts:
                    raise RetryExhaustedError(
                        f"{label}: gave up after {attempt} attempts",
                        last_error=e, attempts=attempt) from e
                delay = self.backoff(attempt)
                if self.deadline is not None and \
                        self._clock() - start + delay > self.deadline:
                    raise RetryExhaustedError(
                        f"{label}: retry deadline ({self.deadline:.3g}s) "
                        f"exceeded after {attempt} attempts",
                        last_error=e, attempts=attempt) from e
                if on_retry is not None:
                    on_retry(attempt, e)   # may abort (donation guard)
                # counted only after the budget checks AND on_retry
                # passed: an exhausted budget or an aborted retry never
                # slept, so it is not a retry
                if _mon.enabled():
                    reg = _mon.get_registry()
                    reg.counter(
                        _mon.RESILIENCE_RETRIES,
                        help="transient failures retried with backoff"
                    ).inc()
                    reg.histogram(
                        _mon.RESILIENCE_BACKOFF_SECONDS,
                        help="seconds slept between retry attempts"
                    ).observe(delay)
                if delay:
                    self._sleep(delay)


class CircuitBreaker:
    """Closed/open/half-open breaker guarding a repeatedly-failing
    dependency (e.g. the inference collector thread restart path).

    Thread-safe; `clock` injectable so tests drive the cooldown without
    sleeping."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold=5, cooldown=30.0,
                 clock=time.monotonic, name="breaker"):
        self.failure_threshold = int(failure_threshold)
        self.cooldown = float(cooldown)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    @property
    def state(self):
        with self._lock:
            return self._state_locked()

    def _state_locked(self):
        if self._state == self.OPEN and not self._probe_inflight and \
                self._clock() - self._opened_at >= self.cooldown:
            self._state = self.HALF_OPEN
        return self._state

    def allow(self):
        """True when a call may proceed (CLOSED, or the single HALF_OPEN
        probe after cooldown). OPEN — and HALF_OPEN with the probe still
        out — sheds without trying."""
        with self._lock:
            st = self._state_locked()
            if st == self.CLOSED:
                return True
            if st == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._probe_inflight = False
            self._state = self.CLOSED
            self._failures = 0

    def record_failure(self):
        with self._lock:
            probe_failed = self._probe_inflight or \
                self._state == self.HALF_OPEN
            self._probe_inflight = False
            self._failures += 1
            tripped = (self._state != self.OPEN or probe_failed) and \
                (probe_failed or self._failures >= self.failure_threshold)
            if tripped:
                self._state = self.OPEN
                self._opened_at = self._clock()
        if tripped and _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_BREAKER_TRIPS,
                labels={"breaker": self.name},
                help="circuit breaker transitions to OPEN").inc()

    def call(self, fn, *args, **kwargs):
        """Guarded call: sheds with `CircuitOpenError` when OPEN,
        otherwise runs `fn` and records the verdict."""
        if not self.allow():
            raise CircuitOpenError(
                f"{self.name}: circuit open "
                f"(cooldown {self.cooldown:.3g}s)")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
