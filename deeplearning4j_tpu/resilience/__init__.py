"""Resilience subsystem: retry/backoff, circuit breaking, deterministic
fault injection, and checkpoint-resume training (≡ the reference's
SharedTrainingMaster fault tolerance, where a restarted host rejoins
from shared state, generalized into first-class runtime policies).

Pieces:
- `policy` — `RetryPolicy` (exponential backoff + seeded jitter,
  attempt/deadline budgets, OOM-never-retries classifier) and
  `CircuitBreaker` (closed/open/half-open with cooldown);
- `faults` — seeded `FaultPlan` injection at named sites
  (data.next / train.dispatch / checkpoint.save / inference.forward),
  zero-cost-when-disabled hooks in the production paths;
- `trainer` — `FaultTolerantTrainer`: periodic async checkpoints,
  step-accurate `resume_or_init`, retry around transient dispatch
  failures, skip-and-count for corrupt batches;
- `errors` — the typed degradation errors, including the
  `InferenceTimeoutError` / `InferenceOverloadedError` raised by the
  hardened `parallel/inference.py`.

Everything is observable through `monitoring/` as `dl4j.resilience.*`
with one-flag-check overhead when monitoring is off.
"""
from __future__ import annotations

from deeplearning4j_tpu.resilience.errors import (  # noqa: F401
    CircuitOpenError, FatalTrainingError, InferenceOverloadedError,
    InferenceTimeoutError, InjectedFault, ResilienceError,
    RetryExhaustedError, TransientError)
from deeplearning4j_tpu.resilience.faults import (  # noqa: F401
    CHECKPOINT_SAVE, DATA_NEXT, INFERENCE_COLLECTOR, INFERENCE_FORWARD,
    TRAIN_DISPATCH, FaultPlan, clear_plan, install_plan)
from deeplearning4j_tpu.resilience.policy import (  # noqa: F401
    CircuitBreaker, RetryPolicy, default_classifier)

__all__ = [
    "ResilienceError", "TransientError", "RetryExhaustedError",
    "CircuitOpenError", "InferenceTimeoutError",
    "InferenceOverloadedError", "InjectedFault", "FatalTrainingError",
    "RetryPolicy", "CircuitBreaker", "default_classifier",
    "FaultPlan", "install_plan", "clear_plan",
    "DATA_NEXT", "TRAIN_DISPATCH", "CHECKPOINT_SAVE",
    "INFERENCE_FORWARD", "INFERENCE_COLLECTOR",
    "FaultTolerantTrainer",
]


def __getattr__(name):
    # FaultTolerantTrainer imports parallel/elastic.py, which imports
    # this package back through parallel/inference.py — resolved lazily
    # so `import deeplearning4j_tpu.resilience` never cycles
    if name == "FaultTolerantTrainer":
        from deeplearning4j_tpu.resilience.trainer import \
            FaultTolerantTrainer
        return FaultTolerantTrainer
    raise AttributeError(name)
