"""Resilience subsystem: retry/backoff, circuit breaking, deterministic
fault injection, checkpoint-resume training (≡ the reference's
SharedTrainingMaster fault tolerance, where a restarted host rejoins
from shared state, generalized into first-class runtime policies) — and
the training GUARDIAN layer that protects the model state itself.

Pieces:
- `policy` — `RetryPolicy` (exponential backoff + seeded jitter,
  attempt/deadline budgets, OOM-never-retries classifier) and
  `CircuitBreaker` (closed/open/half-open with cooldown);
- `faults` — seeded `FaultPlan` injection at named sites
  (data.next / train.dispatch / checkpoint.save / checkpoint.restore /
  checkpoint.corrupt / eval.forward / inference.*),
  zero-cost-when-disabled hooks in the production paths;
- `trainer` — `FaultTolerantTrainer`: periodic async checkpoints,
  step-accurate `resume_or_init`, retry around transient dispatch
  failures, skip-and-count for corrupt batches, and the guardian/
  watchdog driver (reduced-LR batch retry, checkpoint rollback,
  health-gated saves);
- `guardian` — `TrainingGuardian`: device-side divergence detection
  (loss/grad-norm health folded into the jitted step, zero extra host
  syncs) with the skip → reduce-LR → rollback → `DivergenceError`
  escalation ladder;
- `integrity` — checkpoint manifests (per-leaf checksums, atomic
  rename) and verified restore with previous-generation fallback;
- `watchdog` — `StallWatchdog`: per-trainer heartbeats + a monitor
  thread that dumps a full crash report when a step exceeds
  `DL4J_STALL_TIMEOUT`;
- `errors` — the typed degradation errors.

Everything is observable through `monitoring/` (`dl4j.resilience.*`,
`dl4j.guardian.*`, `dl4j.watchdog.*`) with one-flag-check overhead when
monitoring is off, and summarized at `GET /health` on the UI server
(`health_snapshot()`).
"""
from __future__ import annotations

from deeplearning4j_tpu.resilience.errors import (  # noqa: F401
    CheckpointIntegrityError, CircuitOpenError, DistributedInitError,
    DivergenceError, FatalTrainingError, FleetDeadError,
    InferenceOverloadedError, InferenceTimeoutError, InjectedFault,
    MemoryPressureError, PeerDesyncError, PeerLostError,
    PreemptionSignal, ReplayDivergedError, ResilienceError,
    RetryExhaustedError, ServerDeadError, TransientError)
from deeplearning4j_tpu.resilience.faults import (  # noqa: F401
    CACHE_GROW, CHECKPOINT_CORRUPT, CHECKPOINT_RESTORE, CHECKPOINT_SAVE,
    COMM_ALLREDUCE, COMM_BARRIER, DATA_NEXT, EVAL_FORWARD,
    EXECUTABLES_LOAD, GENERATION_ADMIT, GENERATION_STEP, HOST_PREEMPT,
    INFERENCE_COLLECTOR, INFERENCE_FORWARD, REPLICA_RESTART,
    ROUTER_DISPATCH, SERVING_DISPATCH, TRAIN_DISPATCH, FaultPlan,
    clear_plan, install_plan)
from deeplearning4j_tpu.resilience.guardian import (  # noqa: F401
    TrainingGuardian)
from deeplearning4j_tpu.resilience.policy import (  # noqa: F401
    CircuitBreaker, RetryPolicy, default_classifier)
from deeplearning4j_tpu.resilience.watchdog import (  # noqa: F401
    StallWatchdog)

__all__ = [
    "ResilienceError", "TransientError", "RetryExhaustedError",
    "CircuitOpenError", "InferenceTimeoutError",
    "InferenceOverloadedError", "InjectedFault", "FatalTrainingError",
    "DivergenceError", "CheckpointIntegrityError",
    "DistributedInitError", "PeerLostError", "PeerDesyncError",
    "PreemptionSignal", "ServerDeadError", "FleetDeadError",
    "MemoryPressureError", "ReplayDivergedError",
    "RetryPolicy", "CircuitBreaker", "default_classifier",
    "FaultPlan", "install_plan", "clear_plan",
    "DATA_NEXT", "TRAIN_DISPATCH", "CHECKPOINT_SAVE",
    "CHECKPOINT_RESTORE", "CHECKPOINT_CORRUPT", "EVAL_FORWARD",
    "INFERENCE_FORWARD", "INFERENCE_COLLECTOR",
    "COMM_ALLREDUCE", "COMM_BARRIER", "HOST_PREEMPT",
    "GENERATION_STEP", "GENERATION_ADMIT", "CACHE_GROW",
    "EXECUTABLES_LOAD", "SERVING_DISPATCH",
    "ROUTER_DISPATCH", "REPLICA_RESTART",
    "TrainingGuardian", "StallWatchdog", "health_snapshot",
    "FaultTolerantTrainer",
]


def health_snapshot():
    """The `GET /health` payload: overall status plus the installed
    guardian's, watchdog's, multi-host coordinator's, serving
    (GenerationServer), fleet-router, and SLO-tracker introspection
    snapshots (None when not installed). Status ladder: a latched stall, a lost peer, a
    dead serving loop, or an exhausted guardian makes the process
    unhealthy; a guardian mid-escalation, a pending preemption, a
    serving memory-pressure degradation, or an SLO BREACH (the violated
    objective is named in the "slo" section) reports degraded —
    breaches auto-recover with the burn rate, so the degradation clears
    itself. The coordinator snapshot carries the per-process PEER TABLE
    (heartbeat step/age, steps/s, exchange bytes, preempt flags, lost
    verdicts) and, on process 0 of a multi-host run, the cluster
    metrics-plane meta (per-host snapshot ages)."""
    import sys
    from deeplearning4j_tpu.resilience import guardian as _guardian
    from deeplearning4j_tpu.resilience import watchdog as _watchdog
    g = _guardian.ACTIVE
    w = _watchdog.ACTIVE
    try:
        from deeplearning4j_tpu.parallel import coordination as _coord
        c = _coord.ACTIVE
    except Exception:  # noqa: BLE001 — health must always answer
        c = None
    gsnap = g.snapshot() if g is not None else None
    wsnap = w.snapshot() if w is not None else None
    csnap = c.snapshot() if c is not None else None
    # serving states come from sys.modules, never a fresh import: a
    # dashboard-only process must not pull jax in from its health tick
    ssnap = None
    _gen = sys.modules.get("deeplearning4j_tpu.generation.server")
    if _gen is not None:
        try:
            ssnap = [s.serving_state() for s in list(_gen._SERVERS)]
        except Exception:  # noqa: BLE001 — health must always answer
            ssnap = None
    # fleet routers (generation/fleet.py): compact per-router view —
    # replica healths + the autoscale signal; same sys.modules
    # discipline as the serving states above
    fsnap = None
    _fl = sys.modules.get("deeplearning4j_tpu.generation.fleet")
    if _fl is not None:
        try:
            fsnap = [r.fleet_state() for r in list(_fl._ROUTERS)]
        except Exception:  # noqa: BLE001 — health must always answer
            fsnap = None
    # SLO tracker: evaluation is PULL-driven from right here (rate-
    # limited inside the tracker) — nothing on a hot path ever pays it
    slosnap = None
    _slo = sys.modules.get("deeplearning4j_tpu.monitoring.slo")
    if _slo is not None and _slo.ACTIVE is not None:
        try:
            slosnap = _slo.ACTIVE.snapshot()
        except Exception:  # noqa: BLE001 — health must always answer
            slosnap = None
    status = "ok"
    if gsnap is not None and gsnap["status"] == "degraded":
        status = "degraded"
    if ssnap and any(s["state"] == "degraded" for s in ssnap):
        status = "degraded"
    if fsnap and any(f["state"] == "degraded" for f in fsnap):
        status = "degraded"
    if slosnap is not None and slosnap.get("violated"):
        status = "degraded"
    if csnap is not None and (csnap["preempt_requested"]
                              or csnap["preempted"]):
        status = "degraded"
    if wsnap is not None and wsnap["stalled"]:
        status = "stalled"
    if csnap is not None and csnap["lost"]:
        status = "peer_lost"
    if gsnap is not None and gsnap["status"] == "diverged":
        status = "diverged"
    if ssnap and any(s["state"] == "dead" for s in ssnap):
        status = "serving_dead"
    if fsnap and any(f["state"] == "dead" for f in fsnap):
        status = "serving_dead"
    return {"status": status, "guardian": gsnap, "watchdog": wsnap,
            "distributed": csnap, "serving": ssnap, "fleet": fsnap,
            "slo": slosnap}


def __getattr__(name):
    # FaultTolerantTrainer imports parallel/elastic.py, which imports
    # this package back through parallel/inference.py — resolved lazily
    # so `import deeplearning4j_tpu.resilience` never cycles
    if name == "FaultTolerantTrainer":
        from deeplearning4j_tpu.resilience.trainer import \
            FaultTolerantTrainer
        return FaultTolerantTrainer
    raise AttributeError(name)
