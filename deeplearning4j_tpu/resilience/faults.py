"""Deterministic, seeded fault injection (the harness the resilience
tests drive; ≡ testing the reference's SharedTrainingMaster by killing
Spark workers on schedule, but in-process and reproducible).

Production code consults the harness through zero-cost-when-disabled
hooks at named sites:

    DATA_NEXT          "data.next"          — batch pulled from iterator
    TRAIN_DISPATCH     "train.dispatch"     — before the jitted step runs
    CHECKPOINT_SAVE    "checkpoint.save"    — before an async ckpt save
    CHECKPOINT_RESTORE "checkpoint.restore" — before a ckpt restore read
    CHECKPOINT_CORRUPT "checkpoint.corrupt" — inside manifest verification
                                              (a fault here simulates a
                                              corrupted checkpoint and
                                              proves the previous-
                                              generation fallback)
    EVAL_FORWARD       "eval.forward"       — before an eval-loop forward
    INFERENCE_FORWARD  "inference.forward"  — before a coalesced forward
    GENERATION_STEP    "generation.step"    — before a decode-step dispatch
    GENERATION_SUPERSTEP "generation.superstep" — before a multi-token
                                              superstep-block dispatch
    GENERATION_ADMIT   "generation.admit"   — before a prefill admission
    CACHE_GROW         "cache.grow"         — before a KV-cache rung growth
    CACHE_PAGE         "cache.page"         — before paged-KV page work
                                              (admission mapping / block
                                              allocate + CoW + table)
    EXECUTABLES_LOAD   "executables.load"   — on the AOT store miss path
    SERVING_DISPATCH   "serving.dispatch"   — inside the AOT serving path
    HOST_JOIN          "host.join"          — during elastic join admission
    WIRE_DECODE        "wire.decode"        — before a sparse-wire exchange

The hook at every call site is literally

    if _faults.ACTIVE is not None:
        _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)

— one module-attribute check, no allocation, nothing else on the
disabled (production) path. `ACTIVE` is only ever set by an installed
`FaultPlan`.

A plan is a list of seeded rules per site: fail exactly at call N, every
nth call, or with probability p (seeded `random.Random`, so the same
plan replays the same fault schedule). Rules raise `InjectedFault`
(classified transient → exercises retry) unless given another exception
factory (e.g. `FatalTrainingError` to simulate a kill, or an OOM-shaped
RuntimeError to prove retry refuses it).
"""
from __future__ import annotations

import os
import random
import threading

from deeplearning4j_tpu.resilience.errors import InjectedFault

__all__ = ["FaultPlan", "install_plan", "clear_plan", "ACTIVE",
           "DATA_NEXT", "TRAIN_DISPATCH", "CHECKPOINT_SAVE",
           "CHECKPOINT_RESTORE", "CHECKPOINT_CORRUPT", "EVAL_FORWARD",
           "INFERENCE_FORWARD", "INFERENCE_COLLECTOR",
           "COMM_ALLREDUCE", "COMM_BARRIER", "HOST_PREEMPT",
           "GENERATION_STEP", "GENERATION_SUPERSTEP",
           "GENERATION_ADMIT", "CACHE_GROW", "CACHE_PAGE",
           "EXECUTABLES_LOAD", "SERVING_DISPATCH",
           "HOST_JOIN", "WIRE_DECODE",
           "ROUTER_DISPATCH", "REPLICA_RESTART",
           "PROCESS_ID", "resolve_process_id"]

DATA_NEXT = "data.next"
TRAIN_DISPATCH = "train.dispatch"
CHECKPOINT_SAVE = "checkpoint.save"
CHECKPOINT_RESTORE = "checkpoint.restore"
#: fires inside manifest verification (resilience/integrity.py) — a
#: fault here is indistinguishable from a corrupted checkpoint, so the
#: restore path must fall back to the previous generation
CHECKPOINT_CORRUPT = "checkpoint.corrupt"
EVAL_FORWARD = "eval.forward"
INFERENCE_FORWARD = "inference.forward"
#: fires in the collector LOOP (outside the per-batch try), so a fault
#: here kills the collector thread itself — the scenario the breaker-
#: guarded auto-restart exists for
INFERENCE_COLLECTOR = "inference.collector"
#: fires before a multi-host train-step dispatch whose jitted body
#: crosses processes (the compressed gradient all-reduce) — a fault
#: here simulates a DCN transport blip mid-exchange
COMM_ALLREDUCE = "comm.allreduce"
#: fires before a cross-process coordination barrier / heartbeat
#: exchange — the peer-containment paths must surface these as
#: PeerLostError, never an indefinite hang
COMM_BARRIER = "comm.barrier"
#: fires at the multi-host sync point; inject a
#: `PreemptionSignal` here to simulate SIGTERM delivery on schedule
#: (the coordinated drain + checkpoint + clean exit path)
HOST_PREEMPT = "host.preempt"
#: fires before the GenerationServer's per-token decode dispatch — a
#: fault here kills the step mid-flight (donated state presumed gone);
#: crash-replay must re-admit every surviving request bit-identically
GENERATION_STEP = "generation.step"
#: fires before a multi-token decode-block dispatch (superstep k > 1
#: scans AND drafting verify rounds): a fault here kills the whole
#: k-token block mid-flight — crash-replay must regenerate every
#: undelivered token of the block bit-identically
GENERATION_SUPERSTEP = "generation.superstep"
#: fires before a prompt-prefill admission dispatch (fresh or replay);
#: the request is journaled first, so a fault here replays it
GENERATION_ADMIT = "generation.admit"
#: fires before a KV-cache rung-growth dispatch; inject an OOM-shaped
#: error here to drive the memory-pressure degradation ladder
CACHE_GROW = "cache.grow"
#: fires before paged-KV page work (admission page mapping; the
#: per-block allocate/CoW/table build) — inject
#: `PagePoolExhaustedError` to exercise pool exhaustion (contained
#: refusal at admission, degradation ladder + crash-replay mid-stream)
#: or any error to simulate a corrupt page index the replay must
#: rebuild bit-identically
CACHE_PAGE = "cache.page"
#: fires on the AOT executable-store miss path (disk load / live
#: compile) — simulates a corrupt or unreachable executable cache
EXECUTABLES_LOAD = "executables.load"
#: fires inside the AOT serving dispatch (`_serve_aot`) — a fault here
#: must open the AOT breaker and degrade to the legacy path, then
#: recover through the half-open probe after cooldown
SERVING_DISPATCH = "serving.dispatch"
#: fires during elastic join admission — after the joiner announced
#: itself but before the membership commit. A fault here simulates the
#: joiner (or an admitting member) dying mid-join: the transition must
#: be abandoned typed (`MembershipChangeError`), the old roster stays
#: authoritative, and survivors keep training
HOST_JOIN = "host.join"
#: fires before a sparse-wire train-step dispatch (the allgather +
#: decode-and-accumulate exchange) — simulates a corrupt/truncated
#: sparse gradient message; containment must be a typed error or a
#: guardian-gated step, never a silently wrong delivered gradient
WIRE_DECODE = "wire.decode"
#: fires in the FleetRouter before handing a request to the replica it
#: routed to — a fault here is a dispatch-path blip the router must
#: absorb inside the request's bounded failover budget (re-route, never
#: a client-visible error while a healthy replica remains)
ROUTER_DISPATCH = "router.dispatch"
#: fires in the fleet replica supervisor before building a dead
#: replica's replacement — a fault here simulates a restart that itself
#: fails: the replica stays out of the roster, surviving replicas keep
#: serving, and only zero live replicas latches `FleetDeadError`
REPLICA_RESTART = "replica.restart"

#: THE switch production hooks check. None → injection off (the
#: permanent state outside resilience tests).
ACTIVE = None

#: this process's id in a multi-host run — set by the distributed
#: bootstrap (parallel/multihost.initialize) so FaultPlan seed
#: derivation is process-aware without importing jax here. None until
#: a bootstrap (or test) sets it; env vars are the fallback.
PROCESS_ID = None


def resolve_process_id(explicit=None):
    """The process id used for per-worker seed derivation: an explicit
    value wins, then the bootstrap-registered `PROCESS_ID`, then the
    `DL4J_PROCESS_ID` / `JAX_PROCESS_ID` env vars, else 0 (single
    process)."""
    if explicit is not None:
        return int(explicit)
    if PROCESS_ID is not None:
        return int(PROCESS_ID)
    for env in ("DL4J_PROCESS_ID", "JAX_PROCESS_ID"):
        v = os.environ.get(env)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


class _Rule:
    __slots__ = ("kind", "arg", "make", "max_fires", "fires")

    def __init__(self, kind, arg, make, max_fires):
        self.kind = kind          # "at" | "every" | "prob"
        self.arg = arg
        self.make = make
        self.max_fires = max_fires
        self.fires = 0

    def matches(self, call_n, rng):
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.kind == "at":
            return call_n == self.arg
        if self.kind == "every":
            return call_n % self.arg == 0
        return rng.random() < self.arg          # "prob"


def _default_exc(site, call_n):
    return InjectedFault(f"injected fault at {site} (call {call_n})")


class FaultPlan:
    """Seeded schedule of failures at named injection sites.

    Usage:
        plan = (FaultPlan(seed=7)
                .fail_at(TRAIN_DISPATCH, 17, exc=FatalTrainingError("kill"))
                .every(INFERENCE_FORWARD, 3)
                .probability(DATA_NEXT, 0.05))
        with plan:                  # installs/clears the global hook
            ... run training ...
        plan.fired[TRAIN_DISPATCH]  # how many faults actually fired
    """

    def __init__(self, seed=0, process_id=None):
        """Seed derivation is PROCESS-AWARE: the effective rng seed is
        `seed ^ process_id` (explicit arg, else the bootstrap-registered
        process id, else env — see `resolve_process_id`). Every worker
        in a multi-process chaos run installs the same plan with the
        same `seed`, yet probability rules fire on a schedule unique to
        (and deterministic for) each worker — replaying the run replays
        the exact same per-worker fault schedule. Deterministic rules
        (`fail_at` / `every`) are unaffected: they count calls, not
        random draws."""
        self._rules = {}            # site -> [_Rule]
        self._calls = {}            # site -> call count (1-based)
        self.fired = {}             # site -> faults raised
        self.seed = int(seed)
        self.process_id = resolve_process_id(process_id)
        self._rng = random.Random(self.seed ^ self.process_id)
        self._lock = threading.Lock()

    # -- rule builders (chainable) --------------------------------------
    def _add(self, site, kind, arg, exc, max_fires):
        make = exc if callable(exc) else (
            None if exc is None else (lambda *_: exc))
        self._rules.setdefault(site, []).append(
            _Rule(kind, arg, make, max_fires))
        return self

    def fail_at(self, site, call_n, exc=None):
        """Raise on exactly the `call_n`-th (1-based) visit to `site`."""
        return self._add(site, "at", int(call_n), exc, max_fires=1)

    def every(self, site, nth, exc=None, max_fires=None):
        """Raise on every `nth` visit to `site`."""
        if int(nth) < 1:
            raise ValueError("nth must be >= 1")
        return self._add(site, "every", int(nth), exc, max_fires)

    def probability(self, site, p, exc=None, max_fires=None):
        """Raise with probability `p` per visit (seeded, replayable)."""
        return self._add(site, "prob", float(p), exc, max_fires)

    # -- the hot hook ----------------------------------------------------
    def fire(self, site):
        """Called by production hooks while this plan is installed:
        count the visit and raise if a rule matches. Thread-safe (the
        inference sites fire from collector threads)."""
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            exc = None
            for rule in self._rules.get(site, ()):
                if rule.matches(n, self._rng):
                    rule.fires += 1
                    self.fired[site] = self.fired.get(site, 0) + 1
                    make = rule.make or _default_exc
                    exc = make(site, n)
                    break
        if exc is None:
            return
        from deeplearning4j_tpu import monitoring as _mon
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_FAULTS_INJECTED, labels={"site": site},
                help="faults raised by the injection harness").inc()
            from deeplearning4j_tpu.monitoring import events as _events
            _events.emit("resilience", _events.FAULT_INJECTED,
                         attrs={"site": site, "call": n,
                                "error": type(exc).__name__})
        raise exc

    def calls(self, site):
        """How many times `site` has been visited under this plan."""
        with self._lock:
            return self._calls.get(site, 0)

    def reset_counts(self):
        """Clear visit/fire counts but keep the rules (a 'restarted
        process' sees fresh call numbering; rule fire budgets persist so
        a fail-once kill does not re-kill the resumed run)."""
        with self._lock:
            self._calls.clear()
        return self

    # -- install/clear ---------------------------------------------------
    def install(self):
        global ACTIVE
        ACTIVE = self
        return self

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        clear_plan()
        return False


def install_plan(plan):
    return plan.install()


def clear_plan():
    global ACTIVE
    ACTIVE = None
