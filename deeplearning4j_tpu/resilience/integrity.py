"""Checkpoint integrity manifests (the "trust but verify" half of the
training guardian).

PR 2's resilience story assumed a checkpoint that exists is a checkpoint
that is *good*. Two ways that fails in production: a kill mid-save
leaves a truncated/partial step on disk (orbax's atomic rename mostly
prevents this, but the manifest closes the gap for the bytes
themselves), and — worse — a run that diverged BEFORE the save
faithfully persists NaN params, so resume restores garbage
(resilience/guardian.py now gates saves on health, and the manifest
records that verdict durably).

Every `ElasticCheckpointer.save` writes a sidecar manifest under
`<directory>/manifests/<step>.json` via write-tmp + atomic
`os.replace`:

    {"step": N, "leaf_count": K, "treedef": "...",
     "checksums": ["crc32:...", ...],        # per leaf, tree order
     "guardian": "verified" | "unguarded",   # health verdict at save
     "format": 1}

The manifest is computed from the SAME host snapshot the async save
serializes, so it costs no extra device sync and cannot race the next
step's donated buffers.

On restore, `verify_restored` recomputes per-leaf checksums of what
orbax handed back and compares: any mismatch (or non-finite params, or
a missing/truncated manifest file for a manifest-bearing directory)
raises `CheckpointIntegrityError`, and
`ElasticCheckpointer.restore_verified` falls back to the PREVIOUS
generation — counted on `dl4j.resilience.ckpt_restore_fallbacks`. The
`checkpoint.corrupt` fault-injection site fires inside verification so
tests prove the fallback path without hand-corrupting orbax internals.
"""
from __future__ import annotations

import json
import os
import zlib

import numpy as np

from deeplearning4j_tpu.resilience.errors import CheckpointIntegrityError

__all__ = [
    "leaf_finite", "manifest_path", "prune_manifests", "read_manifest",
    "sweep_orphans", "tree_finite", "verify_restored", "write_manifest",
]

_FORMAT = 1
_MANIFEST_DIR = "manifests"


# -- finiteness (the canonical leaf check; resilience/trainer.py._finite
# delegates here) -----------------------------------------------------------
def leaf_finite(a):
    """True when `a` contains no NaN/Inf. Handles python scalars, ints,
    bools, numpy/jax arrays, AND exotic float dtypes: ml_dtypes floats
    (bfloat16, float8_*) register with numpy as void-kind ('V'), so a
    plain `np.issubdtype(dtype, np.floating)` gate silently reported
    bfloat16 NaNs as finite. Non-numeric leaves (strings, objects) have
    nothing to check and are finite by definition."""
    if a is None:
        return True
    if isinstance(a, (bool, int)):
        return True
    if getattr(a, "is_fully_addressable", True) is False:
        # multi-host leaf: check the local shard (the full value when
        # replicated); a genuinely remote-sharded leaf has nothing
        # checkable here and passes — its owning process checks it
        if getattr(a, "is_fully_replicated", False):
            a = a.addressable_shards[0].data
        else:
            shards = getattr(a, "addressable_shards", ())
            return all(leaf_finite(s.data) for s in shards)
    arr = np.asarray(a)
    kind = arr.dtype.kind
    if kind in "iub?SUO":          # ints/uints/bools/str/bytes/objects
        return True
    if kind in "fc":
        return bool(np.isfinite(arr).all())
    # ml_dtypes floats (bfloat16 & friends) land here as kind 'V':
    # upcast to float32 — exactly representable, NaN/Inf preserved
    try:
        return bool(np.isfinite(arr.astype(np.float32)).all())
    except (TypeError, ValueError):
        return True                # not float-like: nothing to check


def tree_finite(tree):
    """True when every leaf of the pytree passes `leaf_finite`."""
    import jax
    return all(leaf_finite(l) for l in jax.tree_util.tree_leaves(tree))


# -- manifest write/read ----------------------------------------------------
def _leaf_checksum(leaf):
    """crc32 over the leaf's raw bytes (host copy if device-resident),
    prefixed so the algorithm can evolve without ambiguity. A REPLICATED
    multi-host leaf checksums through its local shard (every process
    holds the full value — this is what lets multi-host peers verify a
    manifest against their own snapshot); genuinely sharded multi-host
    leaves cannot be gathered here and record (and verify) as "skip"."""
    if getattr(leaf, "is_fully_addressable", True) is False:
        if getattr(leaf, "is_fully_replicated", False):
            leaf = leaf.addressable_shards[0].data
        else:
            return "skip"
    arr = np.ascontiguousarray(np.asarray(leaf))
    try:
        # zero-copy: crc straight over the array's memory — tobytes()
        # would duplicate every leaf on top of the save's host snapshot,
        # doubling the training thread's stall at each save boundary
        data = memoryview(arr).cast("B")
    except (BufferError, TypeError, ValueError):
        data = arr.tobytes()       # exotic dtype refused buffer export
    return f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def manifest_path(directory, step):
    return os.path.join(str(directory), _MANIFEST_DIR, f"{int(step)}.json")


def write_manifest(directory, step, state, verdict=None):
    """Write the integrity manifest for `state` (the exact pytree handed
    to orbax) via tmp-file + atomic rename, so a kill mid-write leaves
    either the old manifest or none — never a truncated one. Returns
    the manifest path."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(state)
    doc = {
        "format": _FORMAT,
        "step": int(step),
        "leaf_count": len(leaves),
        "treedef": str(treedef),
        "checksums": [_leaf_checksum(l) for l in leaves],
        "guardian": verdict if verdict is not None else "unguarded",
    }
    path = manifest_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_manifest(directory, step):
    """The parsed manifest dict, or None when the step has none (e.g. a
    checkpoint written before manifests existed). A PRESENT but
    unreadable/truncated manifest raises `CheckpointIntegrityError` —
    that is corruption, not absence."""
    path = manifest_path(directory, step)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"checkpoint step {step}: manifest {path} is unreadable "
            f"({e}) — treating the generation as corrupt") from e


def verify_restored(directory, step, state, check_finite=True):
    """Verify a restored `state` pytree against the step's manifest:
    leaf count, per-leaf checksums, and (optionally) finiteness of every
    leaf. Raises `CheckpointIntegrityError` on any mismatch; returns the
    verification verdict string ("verified", or "unverified" when no
    manifest exists for the step)."""
    from deeplearning4j_tpu.resilience import faults as _faults
    if _faults.ACTIVE is not None:
        _faults.ACTIVE.fire(_faults.CHECKPOINT_CORRUPT)
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(state)
    if check_finite:
        for i, leaf in enumerate(leaves):
            if not leaf_finite(leaf):
                raise CheckpointIntegrityError(
                    f"checkpoint step {step}: restored leaf {i} contains "
                    "non-finite values — refusing to resume from "
                    "poisoned state")
    manifest = read_manifest(directory, step)
    if manifest is None:
        return "unverified"
    want_treedef = manifest.get("treedef")
    if want_treedef is not None and want_treedef != str(treedef):
        raise CheckpointIntegrityError(
            f"checkpoint step {step}: restored tree structure does not "
            f"match the manifest's — saved {want_treedef!r}, restored "
            f"{str(treedef)!r}")
    if manifest.get("leaf_count") != len(leaves):
        raise CheckpointIntegrityError(
            f"checkpoint step {step}: manifest records "
            f"{manifest.get('leaf_count')} leaves but restore produced "
            f"{len(leaves)}")
    want = manifest.get("checksums", [])
    for i, leaf in enumerate(leaves):
        got = _leaf_checksum(leaf)
        if want[i] == "skip" or got == "skip":
            continue               # multi-host shard: not verifiable here
        if got != want[i]:
            raise CheckpointIntegrityError(
                f"checkpoint step {step}: leaf {i} checksum {got} != "
                f"manifest {want[i]} — bytes corrupted on disk or in "
                "transit")
    return "verified"


def prune_manifests(directory, keep):
    """Remove sidecar manifests for generations no longer on disk
    (max_to_keep GC removes the step dir, not the sidecar). `keep` is
    the iterable of live step numbers. Best effort; returns the number
    removed."""
    mdir = os.path.join(str(directory), _MANIFEST_DIR)
    try:
        entries = os.listdir(mdir)
    except OSError:
        return 0
    live = {str(int(s)) for s in keep}
    removed = 0
    for e in entries:
        stem = e[:-5] if e.endswith(".json") else e
        if stem.isdigit() and stem not in live:
            try:
                os.remove(os.path.join(mdir, e))
                removed += 1
            except OSError:
                pass
    return removed


# -- startup orphan sweep ---------------------------------------------------
def sweep_orphans(directory):
    """Remove debris a killed run can leave in a checkpoint directory:
    orbax's in-progress temp dirs (`*.orbax-checkpoint-tmp-*`), bare
    `*.tmp` files/dirs (including half-written manifests), and manifests
    whose step directory no longer exists (max_to_keep GC removes the
    step, not the sidecar). Returns the number of entries removed.

    Only safe at STARTUP, before this process issues any save — and the
    directory must not be shared with a concurrently-saving process
    (same rule orbax itself has for its cleanup)."""
    import shutil
    directory = str(directory)
    removed = 0
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0

    def _rm(path):
        nonlocal removed
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
            removed += 1
        except OSError:
            pass                   # best effort: a sweep must never crash

    steps = {e for e in entries
             if e.isdigit() and os.path.isdir(os.path.join(directory, e))}
    for e in entries:
        if ".orbax-checkpoint-tmp" in e or e.endswith(".tmp"):
            _rm(os.path.join(directory, e))
    mdir = os.path.join(directory, _MANIFEST_DIR)
    if os.path.isdir(mdir):
        for e in os.listdir(mdir):
            path = os.path.join(mdir, e)
            if e.endswith(".tmp"):
                _rm(path)
                continue
            stem = e[:-5] if e.endswith(".json") else e
            if stem.isdigit() and stem not in steps:
                _rm(path)
    if removed:
        from deeplearning4j_tpu import monitoring as _mon
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_CKPT_ORPHANS_REMOVED,
                help="orphaned tmp/partial checkpoint entries swept at "
                     "startup").inc(removed)
    return removed
