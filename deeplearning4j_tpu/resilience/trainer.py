"""FaultTolerantTrainer — checkpoint-resume training with retry and
skip-and-count (≡ the reference's SharedTrainingMaster fault tolerance:
a restarted worker rejoins and resumes from the last shared state; here
the shared state is an orbax checkpoint and "rejoin" is
`resume_or_init`).

Two wrapping modes, detected from the wrapped object:

* **network mode** — wraps a `MultiLayerNetwork` / `ComputationGraph`.
  `fit(iterator, epochs=)` drives the model's own per-batch step with:
  periodic async `ElasticCheckpointer` saves of
  (params, opt_state, rng key, bn state, counters); `resume_or_init()`
  on entry, restoring the latest checkpoint and SKIPPING the iterator
  batches that run already consumed — step-accurate, so a resumed run
  reaches params bit-identical to an uninterrupted one (the rng key is
  checkpointed, so the retry/resume replay uses the exact key stream);
  retry-with-backoff around transient dispatch failures (model state is
  snapshotted before each attempt and restored before a retry, so a
  half-mutated attempt never leaks into the replay); and skip-and-count
  for corrupt/non-finite batches instead of crashing.

* **sharded mode** — wraps a `ShardedTrainer`-style functional trainer
  (`init`/`fit_batch`). `resume_or_init(init_params)` returns restored
  (params, opt_state) re-placed on the trainer's mesh;
  `fit_batch(params, opt_state, batch, rng)` adds the same retry, skip,
  and periodic-save behavior. Deterministic resume here requires the
  caller to derive `rng` from `trainer.step` (e.g.
  `jax.random.fold_in(root, step)`), since the rng lives with the
  caller in the functional style.

Every resume, retry, skipped batch, and save is observable through
`monitoring/` (`dl4j.resilience.*`) at zero cost when monitoring is
disabled.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import guardian as _guardian
from deeplearning4j_tpu.resilience import integrity as _integrity
from deeplearning4j_tpu.resilience import watchdog as _watchdog
from deeplearning4j_tpu.resilience.errors import (CheckpointIntegrityError,
                                                  DivergenceError,
                                                  FatalTrainingError)
from deeplearning4j_tpu.resilience.policy import RetryPolicy

__all__ = ["FaultTolerantTrainer"]

# canonical implementation in integrity.leaf_finite — it handles scalar
# int/float leaves AND exotic float dtypes (bfloat16 registers with
# numpy as kind 'V', so the old issubdtype(floating) gate silently
# passed bfloat16 NaNs as finite)
_finite = _integrity.leaf_finite


def _dataset_arrays(ds):
    """Feature/label arrays of a DataSet or MultiDataSet (masks are
    weights — a zero there is meaning, not corruption)."""
    feats = getattr(ds, "features", None)
    labs = getattr(ds, "labels", None)
    out = []
    for group in (feats, labs):
        if isinstance(group, (list, tuple)):
            out.extend(group)
        elif group is not None:
            out.append(group)
    return out


class FaultTolerantTrainer:
    def __init__(self, model, directory, save_every=25, max_to_keep=3,
                 retry_policy=None, skip_non_finite=True,
                 max_skipped_batches=None, prefetch=2, guardian=None,
                 watchdog=None, sweep_orphans=True):
        """prefetch: staging-queue depth for the host pipeline in
        network-mode fit() (0 disables). Batch consumption is counted on
        the CONSUMER side of the prefetch queue — i.e. at the training
        loop, in source order — so `step`/resume replay see exactly the
        batches that trained, never ones merely sitting staged in the
        queue: kill/resume stays bit-identical with prefetch on.

        guardian: a `TrainingGuardian` this trainer DRIVES — installed
        around fit(), its reduced-LR escalations re-run the offending
        batch, its rollback requests restore the last verified
        checkpoint in place, and saves are gated on its health verdict
        (a poisoned tree is never persisted; the manifest records the
        verdict).

        watchdog: a `StallWatchdog` armed/disarmed around fit() (the
        caller owns start()/stop() of its monitor thread).

        sweep_orphans: pass False when `directory` is SHARED with other
        concurrently-saving processes (multi-host) — the startup debris
        sweep would delete a peer's in-flight orbax temp dir."""
        from deeplearning4j_tpu.parallel.elastic import ElasticCheckpointer
        self.model = model
        self.guardian = guardian
        self.watchdog = watchdog
        self.prefetch = int(prefetch)
        # our `step` counter (batches consumed) drives save cadence, so
        # the manager itself saves every step it is asked to
        self.ckpt = ElasticCheckpointer(directory, max_to_keep=max_to_keep,
                                        save_interval_steps=1,
                                        sweep_orphans=sweep_orphans)
        self.save_every = int(save_every)
        self.retry = retry_policy or RetryPolicy(max_attempts=3)
        self.skip_non_finite = bool(skip_non_finite)
        self.max_skipped_batches = max_skipped_batches
        self.step = 0              # iterator batches consumed (inc. skipped)
        self.skipped = 0
        self.resumed_step = None   # step restored from, or None
        self._is_network = hasattr(model, "_fit_batch")

    # -- shared bookkeeping ---------------------------------------------
    def _count_skip(self, reason):
        self.skipped += 1
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_BATCHES_SKIPPED,
                labels={"reason": reason},
                help="batches skipped instead of crashing the run").inc()
        if self.max_skipped_batches is not None \
                and self.skipped > self.max_skipped_batches:
            raise FatalTrainingError(
                f"skipped {self.skipped} batches "
                f"(> max_skipped_batches={self.max_skipped_batches}) — "
                "data pipeline looks broken, refusing to train on noise")

    def _note_resume(self, step):
        self.resumed_step = step
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.RESILIENCE_RESUMES,
                        help="checkpoint resumes after restart").inc()
            reg.gauge(_mon.RESILIENCE_RESUME_STEP,
                      help="step the latest resume restored").set(step)

    # ===================== network mode =================================
    def _net_extra(self):
        m = self.model
        # 0-d ndarrays: orbax StandardSave rejects bare numpy scalars
        extra = {"rng_key": np.asarray(m._rng_key),
                 "iteration": np.asarray(int(m._iteration), np.int64),
                 "epoch": np.asarray(int(m._epoch), np.int64),
                 "step": np.asarray(int(self.step), np.int64)}
        if m._state:
            extra["net_state"] = m._state
        return extra

    def _save_network(self, wait=False, verdict=None):
        m = self.model
        self.ckpt.save(self.step, m._params, m._opt_state,
                       extra=self._net_extra(), wait=wait,
                       verdict=verdict)

    def _guardian_allows_save(self, g):
        """THE save gate, shared by both modes: a tree the guardian
        cannot vouch for (mid-escalation, unresolved bad streak) is
        NEVER persisted — the whole point of rollback is that every
        on-disk generation is a known-good target. Withheld saves count
        on dl4j.guardian.saves_gated."""
        if g is None or g.verify_now():
            return True
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.GUARDIAN_SAVES_GATED,
                help="checkpoint saves withheld because the guardian "
                     "could not vouch for the params").inc()
        return False

    def _maybe_save(self, g, wait=False):
        """Gated checkpoint save; the verdict lands in the integrity
        manifest."""
        if not self._guardian_allows_save(g):
            return False
        self._save_network(wait=wait,
                           verdict=None if g is None else "verified")
        return True

    def _drive_guardian(self, g, ds):
        """Consume the guardian's escalation actions after a trained
        batch: RETRY re-runs the SAME batch (the guarded step already
        refused the bad update, so params are still pre-batch, and
        `lr_scale` is now reduced); ROLLBACK restores the newest
        verified checkpoint in place. Bounded by the ladder depth —
        each pass through can escalate at most one rung."""
        for _ in range(g.max_lr_retries + g.max_rollbacks + 1):
            act = g.take_action()
            if act is None:
                return
            if act == _guardian.RETRY:
                self._fit_one(ds)
                continue
            if act == _guardian.ROLLBACK:
                self._rollback_network(g)
                return

    def _load_network_state(self, like, state):
        """Graft restored state into the live model, rebuilding every
        leaf as an XLA-OWNED device array before the donating train
        step ever sees it (see parallel/elastic.xla_owned_copy:
        jnp.asarray zero-copy aliases numpy memory, and donation then
        frees a buffer numpy owns — intermittent heap corruption after
        resume). Uncommitted like init()'s arrays; mesh-sharded leaves
        get the explicit NamedSharding device_put. Returns the restored
        step counter (batches consumed when the checkpoint was
        written)."""
        import jax
        from jax.sharding import NamedSharding

        from deeplearning4j_tpu.parallel.elastic import xla_owned_copy
        m = self.model

        def place(fresh, restored):
            if not hasattr(restored, "shape"):
                return restored
            sh = getattr(fresh, "sharding", None)
            if sh is None:
                return np.array(restored)
            return xla_owned_copy(
                restored, sh if isinstance(sh, NamedSharding) else None)

        state = jax.tree_util.tree_map(place, like, state)
        m._params = state["params"]
        m._opt_state = state["opt_state"]
        extra = state["extra"]
        if "net_state" in extra:
            m._state = extra["net_state"]
        m._rng_key = xla_owned_copy(
            np.asarray(extra["rng_key"], np.uint32))
        m._iteration = int(extra["iteration"])
        # _epoch is deliberately NOT restored: fit() re-walks every epoch
        # from 0 (skipping consumed batches) and increments per pass, so
        # restoring the mid-run value would double-count the replayed
        # epochs (final _epoch = restored + epochs instead of epochs).
        # The checkpointed value stays available in the dump for
        # post-mortems.
        return int(extra["step"])

    def resume_or_init(self):
        """Network mode: restore the newest VERIFIED checkpoint INTO the
        wrapped (already-initialized) model — manifest-checksum and
        finiteness verified, falling back a generation when the latest
        is corrupt (resilience/integrity.py). Returns the restored step
        (batches already consumed by the crashed run), 0 when starting
        fresh."""
        m = self.model
        if m._params is None:
            m.init()
        latest = self.ckpt.latest_step()
        if latest is None:
            return 0
        like = {"params": m._params, "opt_state": m._opt_state,
                "extra": self._net_extra()}
        step, state = self.ckpt.restore_verified(like=like)
        if step is None:
            return 0
        self.step = self._load_network_state(like, state)
        self._note_resume(self.step)
        return self.step

    def _restore_for_rollback(self, like):
        """THE rollback restore both modes share: flush in-flight async
        saves (the newest verified generation may still be writing —
        reading it mid-write would needlessly burn a generation on the
        fallback ladder), restore the newest VERIFIED generation, and
        translate 'nothing restorable' into `DivergenceError`."""
        try:
            self.ckpt.manager.wait_until_finished()
            step, state = self.ckpt.restore_verified(like=like)
        except CheckpointIntegrityError as e:
            raise DivergenceError(
                "guardian requested rollback but no checkpoint "
                "generation could be restored") from e
        if step is None:
            raise DivergenceError(
                "guardian requested rollback but no verified checkpoint "
                "exists yet (diverged before the first save)")
        return step, state

    def _rollback_network(self, g):
        """Guardian-requested rollback: restore the newest verified
        checkpoint IN PLACE (params, opt state, rng, counters — exactly
        the resume path, minus the iterator bookkeeping: `self.step`
        keeps counting real iterator positions so replay alignment and
        save cadence are untouched). Raises `DivergenceError` when
        there is nothing to roll back to."""
        m = self.model
        like = {"params": m._params, "opt_state": m._opt_state,
                "extra": self._net_extra()}
        step, state = self._restore_for_rollback(like)
        restored = self._load_network_state(like, state)
        g.note_rollback(restored)
        return restored

    def _snapshot(self):
        m = self.model
        return (m._params, m._opt_state, m._state, m._rng_key,
                m._iteration, m._epoch, m._score,
                getattr(m, "_params_version", 0))

    def _restore_snapshot(self, snap):
        m = self.model
        (m._params, m._opt_state, m._state, m._rng_key,
         m._iteration, m._epoch, m._score, m._params_version) = snap

    def _fit_one(self, ds):
        """One batch through the model's own step, retrying transient
        dispatch failures. The pre-attempt snapshot is restored before
        every retry so the rng split and counters replay exactly —
        a retried step is bit-identical to a never-failed one.

        The snapshot holds REFERENCES (a per-batch host copy of every
        param would double the step's memory traffic). A failure raised
        BEFORE the jitted dispatch consumes its donated buffers — the
        fault-injection point, enqueue/transfer errors — restores and
        retries cleanly. A failure AFTER donation leaves the snapshot
        pointing at deleted buffers; retrying would crash confusingly,
        so that case re-raises the original error and the process-level
        answer (restart + resume_or_init from the last checkpoint)
        takes over."""
        m = self.model
        snap = self._snapshot()

        def attempt():
            if self._is_multilayer():
                m._fit_batch(ds.features, ds.labels, ds.labelsMask,
                             ds.featuresMask)
            else:
                m._fit_batch(ds)

        def on_retry(attempt_n, exc):
            import jax
            for tree in (snap[0], snap[1], snap[2]):
                for leaf in jax.tree_util.tree_leaves(tree):
                    if getattr(leaf, "is_deleted", lambda: False)():
                        raise exc   # donated mid-dispatch: not retryable
            self._restore_snapshot(snap)

        self.retry.call(attempt, label="train.dispatch",
                        on_retry=on_retry)

    def _is_multilayer(self):
        # ComputationGraph._fit_batch takes the DataSet whole;
        # MultiLayerNetwork's takes unpacked arrays
        cached = getattr(self, "_multilayer_sig", None)
        if cached is None:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            cached = not isinstance(self.model, ComputationGraph)
            self._multilayer_sig = cached
        return cached

    def fit(self, data, epochs=1):
        """Network mode: resume (if a checkpoint exists), then drive the
        iterator. Batch positions the crashed run already consumed are
        skipped — corrupt batches count as consumed, so replay alignment
        holds. A fatal (non-retryable) error waits for in-flight async
        saves before propagating, so the NEXT run's `resume_or_init`
        sees every checkpoint this run completed."""
        if not self._is_network:
            raise TypeError("fit(iterator) is network mode; wrap a "
                            "MultiLayerNetwork/ComputationGraph, or use "
                            "resume_or_init(params)/fit_batch(...) for "
                            "functional trainers")
        already = self.resume_or_init()
        consumed = 0
        # guardian/watchdog scope: install the guardian for the duration
        # of this fit (unless the caller already installed it) and arm
        # the watchdog's stall detection
        g = self.guardian
        g_installed = False
        if g is not None and _guardian.ACTIVE is not g:
            g.install()
            g_installed = True
        elif g is None:
            # a with-block guardian the caller installed (no guardian=
            # kwarg): the guarded step already reports to it, so this
            # fit must also DRIVE it — consume retry/rollback actions
            # and gate saves on its verdict (mirrors sharded fit_batch,
            # which reads ACTIVE too)
            g = _guardian.ACTIVE
        g_prev_driver = None
        if g is not None:
            # this fit DRIVES the guardian (take_action after each
            # batch), so escalation actions must survive mid-batch
            # flushes — a TBPTT segment loop flushes per segment, and a
            # ROLLBACK raised on an early segment has to still be
            # pending when _drive_guardian runs after the batch
            g_prev_driver = g.driver_attached
            g.driver_attached = True
        # arm the watchdog for this fit; arm/disarm nest, so a caller's
        # wider armed window (multi-phase script) or a concurrent fit
        # sharing this watchdog keeps detection on after this one ends
        if self.watchdog is not None:
            self.watchdog.arm()
        # host pipeline: batches stage to XLA-owned device buffers in
        # the background; the finite check happens on the HOST arrays
        # inside the worker (pre-staging), so the consumer loop reads a
        # precomputed verdict instead of forcing a device readback.
        # Resume replay pulls-and-drops the first `already` batches —
        # staging those would waste a host copy + H2D transfer each, so
        # the stage passes them through untouched (worker pull order ==
        # consumer delivery order, so the countdown aligns; each worker
        # error shifts it by one, leaving at most that many trainable
        # batches unstaged — still correct, the fit paths accept raw
        # DataSets).
        from deeplearning4j_tpu.runtime import pipeline as _pipeline
        replay = {"left": already}

        def _stage(ds):
            if replay["left"] > 0:
                replay["left"] -= 1
                return ds
            return _pipeline.stage_dataset(
                ds, check_finite=self.skip_non_finite)

        src, pf = _pipeline.maybe_prefetch(data, self.prefetch,
                                           stage=_stage)
        try:
            for _ in range(int(epochs)):
                with _mon.span("fit.epoch"):
                    if hasattr(src, "reset"):
                        src.reset()
                    # the RAW iterator, spanned manually — traced_iter's
                    # generator would be finalized by the first iterator
                    # exception, silently truncating the epoch on the
                    # very errors this loop exists to skip-and-count
                    it = iter(src)
                    while True:
                        # the injection hook gets its OWN handler: it
                        # fires BEFORE the pull, so the iterator has not
                        # advanced and the real batch must be pulled-
                        # and-dropped to keep `consumed` aligned with
                        # true iterator position (resume replay depends
                        # on it)
                        if _faults.ACTIVE is not None:
                            try:
                                _faults.ACTIVE.fire(_faults.DATA_NEXT)
                            except Exception as e:  # noqa: BLE001
                                if not self.retry.classifier(e):
                                    raise
                                try:
                                    next(it)
                                except StopIteration:
                                    break
                                consumed += 1
                                if consumed > already:
                                    self.step = consumed
                                    self._count_skip("data_fault")
                                continue
                        try:
                            with _mon.span("fit.data_next"):
                                ds = next(it)
                        except StopIteration:
                            break
                        except Exception as e:  # noqa: BLE001
                            if not self.retry.classifier(e):
                                raise
                            # the iterator ITSELF failed mid-pull: that
                            # position is lost (best effort — a broken
                            # pipeline cannot re-serve it, and a
                            # generator-backed iterator may end the
                            # epoch on the next pull); count it consumed
                            # so replay stays aligned with the positions
                            # the iterator actually yielded
                            consumed += 1
                            if consumed > already:
                                self.step = consumed
                                self._count_skip("data_error")
                            if isinstance(src, _pipeline.PrefetchIterator):
                                # the error killed the prefetch worker
                                # and would re-raise forever; restart it
                                # from the base's current position so
                                # skip-and-count proceeds exactly like
                                # the unprefetched path (a permanently
                                # broken loader is still bounded by
                                # max_skipped_batches, as before). `src`,
                                # not `pf`: the user may have handed us an
                                # already-wrapped Async/PrefetchIterator
                                # (pf is None then)
                                src.resume_after_error()
                            continue
                        consumed += 1
                        if consumed <= already:
                            continue       # trained before the crash
                        if self.skip_non_finite:
                            # staged batches carry the worker's host-side
                            # verdict; checking the device arrays here
                            # would block on a D2H readback every step
                            pre = getattr(ds, "_host_finite", None)
                            finite = pre if pre is not None else all(
                                _finite(a) for a in _dataset_arrays(ds))
                            if not finite:
                                self.step = consumed
                                self._count_skip("non_finite")
                                continue
                        self._fit_one(ds)
                        if g is not None:
                            self._drive_guardian(g, ds)
                        self.step = consumed
                        if self.step % self.save_every == 0:
                            self._maybe_save(g)
                    self.model._epoch += 1
            self._maybe_save(g, wait=True)
        except Exception:
            # simulate-kill paths land here: flush in-flight saves so the
            # restart can restore the newest completed checkpoint
            try:
                self.ckpt.manager.wait_until_finished()
            except Exception:  # noqa: BLE001 — the original error wins
                pass
            raise
        finally:
            if g is not None:
                g.driver_attached = g_prev_driver
            if g_installed:
                g.uninstall()    # restore any guardian this one shadowed
            # _fit_one beats through model._fit_batch (never model.fit),
            # so the model fit epilogues' retire never runs here — under
            # a caller-armed wider window the stale beat would age into
            # a false stall trip during the next phase
            if _watchdog.ACTIVE is not None:
                kind = "multilayer" if self._is_multilayer() else "graph"
                _watchdog.ACTIVE.retire(f"{kind}@{id(self.model):x}")
            if self.watchdog is not None:
                self.watchdog.disarm()
            if pf is not None:
                pf.close()
        return self.model

    # ===================== sharded (functional) mode ====================
    def resume_or_init_sharded(self, init_params):
        """Sharded mode: init via the wrapped trainer, then overwrite
        with the latest checkpoint re-placed on the trainer's mesh.
        Returns (params, opt_state); `self.step` holds the restored
        step for the caller's rng derivation."""
        from deeplearning4j_tpu.parallel.elastic import replace_on_mesh
        trainer = self.model
        params, opt_state = trainer.init(init_params)
        latest = self.ckpt.latest_step()
        if latest is None:
            return params, opt_state
        like = {"params": params, "opt_state": opt_state}
        step, state = self.ckpt.restore_verified(like=like)
        if step is None:
            return params, opt_state
        state = replace_on_mesh(trainer.mesh, like, state)
        self.step = int(step)
        self._note_resume(self.step)
        return state["params"], state["opt_state"]

    def _rollback_sharded(self, g, params, opt_state):
        """Guardian rollback, functional style: returns the restored
        (params, opt_state) re-placed on the trainer's mesh — the caller
        simply carries on with them (`fit_batch` returns them
        transparently)."""
        from deeplearning4j_tpu.parallel.elastic import replace_on_mesh
        like = {"params": params, "opt_state": opt_state}
        step, state = self._restore_for_rollback(like)
        state = replace_on_mesh(self.model.mesh, like, state)
        g.note_rollback(int(step))
        return state["params"], state["opt_state"]

    def fit_batch(self, params, opt_state, batch, rng):
        """Sharded mode: one retried step + periodic save. Non-finite
        batches return the inputs unchanged with loss None."""
        trainer = self.model
        # a guardian handed to the constructor is installed here (the
        # functional style has no fit() scope to install it in) — the
        # sharded step only computes its health verdict for the guardian
        # that is ACTIVE at dispatch. Left installed across calls;
        # close() clears it.
        if self.guardian is not None \
                and _guardian.ACTIVE is not self.guardian:
            self.guardian.install()
        if self.guardian is not None:
            # driven every call (take_action below) — actions must not
            # be dropped by an intervening flush; close() resets
            self.guardian.driver_attached = True
        if self.skip_non_finite:
            import jax
            # only HOST-resident leaves are checked: np.asarray on an
            # already-sharded device batch would force a blocking D2H
            # readback every step (and crash on multi-host shards) —
            # callers wanting device-batch validation should check
            # before shard_batch
            leaves = [a for a in jax.tree_util.tree_leaves(batch)
                      if isinstance(a, np.ndarray)]
            if not all(_finite(a) for a in leaves):
                self.step += 1
                self._count_skip("non_finite")
                return params, opt_state, None
        def on_retry(attempt_n, exc):
            # same donation guard as network mode's _fit_one: a failure
            # AFTER the jitted step consumed its donated inputs leaves
            # params/opt_state deleted — re-raise the ORIGINAL error
            # instead of a confusing 'Array has been deleted' retry
            import jax
            for tree in (params, opt_state):
                for leaf in jax.tree_util.tree_leaves(tree):
                    if getattr(leaf, "is_deleted", lambda: False)():
                        raise exc

        params, opt_state, loss = self.retry.call(
            trainer.fit_batch, params, opt_state, batch, rng,
            label="train.dispatch", on_retry=on_retry)
        self.step += 1
        # guardian escalations, functional flavor: the batch's inputs
        # were donated, so the RETRY rung cannot literally re-run it —
        # the reduced lr_scale applies from the next step instead (the
        # guarded step already refused the bad update); ROLLBACK swaps
        # in the restored state transparently
        g = _guardian.ACTIVE
        if g is not None:
            act = g.take_action()
            if act == _guardian.ROLLBACK:
                params, opt_state = self._rollback_sharded(
                    g, params, opt_state)
        if self.step % self.save_every == 0 \
                and self._guardian_allows_save(g):
            self.ckpt.save(self.step, params, opt_state,
                           verdict=None if g is None else "verified")
        return params, opt_state, loss

    def finalize(self, params=None, opt_state=None):
        """Final synchronous save (sharded mode passes the live state;
        network mode reads it off the model) and close. The save is
        GATED like every other: a tree the guardian cannot vouch for is
        not persisted on the way out either — the run ends with the
        last verified generation as the newest on disk."""
        g = self.guardian if self.guardian is not None \
            else _guardian.ACTIVE
        if params is not None:
            if self._guardian_allows_save(g):
                self.ckpt.save(self.step, params, opt_state, wait=True,
                               verdict=None if g is None else "verified")
        elif self._is_network and self.model._params is not None:
            self._maybe_save(g, wait=True)
        self.close()

    def close(self):
        if self.guardian is not None:
            self.guardian.driver_attached = False
            self.guardian.uninstall()
        self.ckpt.close()
