"""Training guardian: device-side divergence detection with an
escalating recovery ladder.

PR 2's resilience layer survives *infrastructure* failure; nothing
guarded *model-state* failure: one overflowing step writes NaN into the
params, and the run is silently dead long before anyone reads the loss
curve. The guardian closes that hole in three pieces:

1. **Device-side health, zero host syncs.** When a guardian is
   installed, the trainers switch to a GUARDED train step (e.g.
   `MultiLayerNetwork._train_step_guarded`) whose jitted body also
   computes the global grad norm and a health verdict

       ok = isfinite(loss) & isfinite(gnorm) & (gnorm <= max_gnorm)

   and applies the parameter/optimizer/state update ONLY when `ok`
   (`jnp.where` select — the same program, so donation still holds and
   a NaN gradient can never reach the live params). The verdict and the
   grad norm stay ON DEVICE; `on_step` just appends the scalars.

2. **Amortized checks.** Every `check_every` steps the pending scalars
   materialize in ONE stacked host read (counted on
   `dl4j.pipeline.syncs{site="guardian"}` — the PR 3 regression harness
   proves the cadence: syncs == steps/check_every, never per-step). The
   flush maintains a host-side EMA of the grad norm; `spike_factor *
   ema` feeds back as the `max_gnorm` threshold the NEXT steps enforce
   on device, so finite-but-exploding steps are skipped too.

3. **The escalation ladder.** Consecutive unhealthy steps climb:
   skip-and-count (implicit — the device already skipped the update) →
   reduce LR and retry the batch (`lr_scale *= lr_backoff`; the guarded
   step multiplies updates by `lr_scale`, and FaultTolerantTrainer
   re-runs the offending batch) → roll back to the last *verified*
   checkpoint (FaultTolerantTrainer restores via the integrity-checked
   path) → raise `DivergenceError`. A clean stretch of
   `recovery_checks` healthy flushes walks the LR back to 1.0.

Install mirrors `resilience/faults.py`: a module-global `ACTIVE`
consulted by the trainers as `if _guardian.ACTIVE is not None:` — one
pointer compare, nothing else, on the disabled (production) path.

    with TrainingGuardian(spike_factor=8.0):
        net.fit(iterator, epochs=3)          # bare fit: skip + LR ladder

    g = TrainingGuardian()
    FaultTolerantTrainer(net, dir, guardian=g).fit(iterator)  # + rollback

State surfaces at `GET /health` on the UI server and as
`dl4j.guardian.*` metrics.
"""
from __future__ import annotations

import time

import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.resilience.errors import DivergenceError

__all__ = ["ACTIVE", "RETRY", "ROLLBACK", "TrainingGuardian",
           "clear_guardian", "guarded_apply"]

#: THE switch the trainer hooks check. None → guardian off (the
#: permanent state unless a TrainingGuardian is installed).
ACTIVE = None

#: escalation actions a driving trainer consumes via `take_action()`
RETRY = "retry_reduced_lr"
ROLLBACK = "rollback"


def guarded_apply(tx, grads, loss, params, opt_state, lr_scale, max_gnorm,
                  constraints=None, extra=()):
    """THE jit-traceable core every guarded train step shares
    (multilayer, TBPTT, graph, sharded — keeping the verdict semantics
    in one place): compute the health verdict

        ok = isfinite(loss) & isfinite(gnorm) & (gnorm <= max_gnorm)

    scale the optimizer updates by `lr_scale` (the reduce-LR rung's
    traced scalar), and apply the update ONLY when healthy — a
    `jnp.where` select in the same donated program, so an unhealthy
    gradient can never reach the live trees. `extra` carries additional
    (new, old) tree pairs to select the same way (bn state, recurrent
    carries). Returns (params, opt_state, selected_extras, gnorm, ok)."""
    import jax
    import jax.numpy as jnp
    import optax
    gnorm = optax.global_norm(grads)
    ok = jnp.isfinite(loss) & jnp.isfinite(gnorm) & (gnorm <= max_gnorm)
    updates, new_opt = tx.update(grads, opt_state, params)
    updates = jax.tree_util.tree_map(lambda u: u * lr_scale, updates)
    new_params = optax.apply_updates(params, updates)
    if constraints is not None:
        new_params = constraints(new_params)

    def keep(new, old):
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(ok, n, o), new, old)

    return (keep(new_params, params), keep(new_opt, opt_state),
            tuple(keep(n, o) for n, o in extra), gnorm, ok)


class TrainingGuardian:
    """Divergence detector + recovery policy over guarded train steps.

    Parameters
    ----------
    check_every: materialize the pending device verdicts every N steps
        (one stacked host read per check — the only sync this class
        ever performs). The default 10 amortizes the read so a guarded
        fit keeps PR 3's host-runs-ahead pipeline; check_every=1 gives
        step-exact escalation at the cost of one host-blocking sync per
        step — the per-step sync the async pipeline exists to avoid.
        Either way NaN can never reach the params: the device-side
        jnp.where gate refuses unhealthy updates regardless of how
        often the host looks.
    ema_decay / spike_factor / warmup_steps: a step whose grad norm
        exceeds ``spike_factor * EMA(grad_norm)`` is unhealthy; the EMA
        needs ``warmup_steps`` healthy samples before spike detection
        (and the device-side ``max_gnorm`` threshold) arms.
    max_skips: consecutive unhealthy steps tolerated (updates already
        skipped on device) before escalating.
    lr_backoff / max_lr_retries: each LR rung multiplies ``lr_scale``
        by ``lr_backoff`` and requests a batch retry.
    max_rollbacks: checkpoint rollbacks granted before the ladder ends
        in `DivergenceError`.
    recovery_checks: fully-healthy flushes required to restore
        ``lr_scale`` to 1.0 and re-arm the lower rungs.
    raise_on_divergence: False returns the model to the caller with
        ``healthy == False`` instead of raising (serving-style
        degradation; the default is to fail loudly).
    """

    def __init__(self, check_every=10, ema_decay=0.98, spike_factor=10.0,
                 warmup_steps=20, max_skips=3, lr_backoff=0.5,
                 max_lr_retries=2, max_rollbacks=2, recovery_checks=3,
                 raise_on_divergence=True):
        if int(check_every) < 1:
            raise ValueError("check_every must be >= 1")
        self.check_every = int(check_every)
        self.ema_decay = float(ema_decay)
        self.spike_factor = float(spike_factor)
        self.warmup_steps = int(warmup_steps)
        self.max_skips = int(max_skips)
        self.lr_backoff = float(lr_backoff)
        self.max_lr_retries = int(max_lr_retries)
        self.max_rollbacks = int(max_rollbacks)
        self.recovery_checks = int(recovery_checks)
        self.raise_on_divergence = bool(raise_on_divergence)

        #: multiplier the guarded step applies to updates (the LR rung)
        self.lr_scale = 1.0
        #: device-side spike threshold for upcoming steps (inf until the
        #: EMA warms up; refreshed every flush)
        self.max_gnorm = float("inf")

        self.step = 0              # guarded steps observed
        self.checks = 0            # flushes performed
        self.skipped = 0           # updates the device refused to apply
        self.lr_retries = 0        # LR rungs climbed since last recovery
        self.rollbacks = 0         # checkpoint rollbacks consumed
        self.last_good_step = 0    # most recent healthy step number
        self.last_restored_step = None  # trainer-step a rollback landed on
        self.healthy = True        # False once the ladder is exhausted
        self._ema = None
        self._ema_n = 0            # healthy samples folded into the EMA
        self._bad_streak = 0       # consecutive unhealthy steps
        self._good_checks = 0      # consecutive fully-healthy flushes
        self._pending = []         # (gnorm, ok, retryable) device scalars
        self._action = None        # RETRY / ROLLBACK for the driver
        self._in_step_flush = False  # flush fired from on_step (vs
        #                              verify_now / __exit__)
        #: a driver (FaultTolerantTrainer) is consuming take_action()
        #: this fit — unconsumed actions survive across flushes instead
        #: of being dropped, because the driver only runs AFTER the
        #: batch: a TBPTT segment loop flushes once per segment, and a
        #: ROLLBACK raised on segment k must still be pending when the
        #: driver looks, not burned by segment k+1's flush
        self.driver_attached = False
        self._climbed_this_flush = False  # one rung max per flush
        self._prev_active = None   # guardian shadowed by install()
        #: when bound to a specific trainer, on_step reports from OTHER
        #: trainers are ignored — a host-local auxiliary guarded fit
        #: must not advance a coordinated guardian's flush cadence (the
        #: multi-host verdict windows must stay step-aligned across
        #: hosts); None (default) accepts every report
        self._bound = None

    # -- install / clear (the faults.py pattern, plus nesting) -----------
    def install(self):
        """Install as ACTIVE, remembering the guardian this one shadows
        so `uninstall()` restores it — an inner scope (e.g.
        FaultTolerantTrainer.fit driving its own guardian inside a
        user's `with TrainingGuardian():` block) must not strip the
        outer guard from the fits that follow it."""
        global ACTIVE
        if ACTIVE is not self:
            self._prev_active = ACTIVE
            ACTIVE = self
        return self

    def uninstall(self):
        """Undo this guardian's install(): restore the guardian it
        shadowed (None when there was none). A no-op unless this
        guardian is the one currently ACTIVE."""
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = getattr(self, "_prev_active", None)
            self._prev_active = None
        return self

    def __enter__(self):
        return self.install()

    def __exit__(self, exc_type, exc, tb):
        try:
            # the fit is over — flush the tail verdicts (steps since the
            # last check_every boundary) so skipped/status are accurate
            # after the with-block; skipped when an exception is already
            # propagating (a DivergenceError from here would mask it)
            if exc_type is None and self._pending:
                self._flush()
        finally:
            self.uninstall()
        return False

    def bind(self, trainer):
        """Scope verdict collection to `trainer`: only reports whose
        `source` IS that trainer feed this guardian's window — while
        bound, source-less reports (call sites that don't plumb a
        source, e.g. a host-local auxiliary MultiLayerNetwork.fit) are
        dropped too, because ANY extra verdict desyncs a coordinated
        window across hosts. None unbinds (every report counts, the
        single-host default)."""
        self._bound = trainer
        return self

    # -- the hot hook ----------------------------------------------------
    def on_step(self, loss, gnorm, ok, retryable=True, source=None):
        """Record one guarded step's device scalars. No host sync here:
        the scalars materialize together at the `check_every` cadence.
        May raise `DivergenceError` from the flush when the ladder is
        exhausted.

        `loss` is accepted for call-site symmetry with the guarded step's
        outputs but is NOT read on the host — the device verdict already
        folded isfinite(loss) into `ok`, so only (gnorm, ok) materialize.

        retryable=False marks steps whose batch must NOT be re-run by
        the RETRY rung (TBPTT segment loops: the healthy segments'
        updates were applied, so re-running the batch would apply them
        twice) — escalation skips straight from the skip rung to
        rollback for those."""
        if self._bound is not None and source is not self._bound:
            return
        self.step += 1
        self._pending.append((gnorm, ok, retryable))
        if len(self._pending) >= self.check_every:
            # mark the flush as step-aligned: the newest pending step IS
            # the batch the driver just ran, so a RETRY issued here
            # targets the right batch (a verify_now/__exit__ flush has
            # no such guarantee and never issues RETRY)
            self._in_step_flush = True
            try:
                self._flush()
            finally:
                self._in_step_flush = False

    def take_action(self):
        """Return-and-clear the pending escalation action (RETRY /
        ROLLBACK / None). Drivers that can act (FaultTolerantTrainer)
        consume this after each step; bare fit loops never call it —
        the LR reduction still applies to their subsequent steps, and
        rollback simply stays unavailable without a checkpointer."""
        act, self._action = self._action, None
        return act

    def verify_now(self):
        """Flush any pending verdicts NOW (one sync — callers align this
        with an already-host-bound moment like a checkpoint save) and
        report whether the CURRENT params are trustworthy: healthy, no
        live bad streak, no unconsumed escalation."""
        if self._pending:
            self._flush()
        return self.healthy and self._bad_streak == 0 \
            and self._action is None

    def note_rollback(self, restored_step):
        """A driver completed a checkpoint rollback: pending verdicts
        refer to discarded state, the EMA restarts (the restored region
        may live at a different gradient scale), and the streak resets
        so the restored run gets a fresh window. `restored_step` is the
        CHECKPOINT'S trainer-step number — a different timeline from
        this guardian's own step counter (a resumed run's guardian
        starts at 0) — so it surfaces as `last_restored_step`, while
        `last_good_step` stays on the guardian timeline: the restored
        state is verified good, so last-good is NOW."""
        self._pending.clear()
        self._bad_streak = 0
        self._good_checks = 0
        self._ema = None
        self._ema_n = 0
        self.max_gnorm = float("inf")
        self.last_restored_step = int(restored_step)
        self.last_good_step = self.step
        if _mon.enabled():
            _events.emit(
                "resilience", _events.GUARDIAN_ROLLBACK,
                attrs={"step": self.step, "phase": "restored",
                       "restored_step": self.last_restored_step},
                correlation_id="guardian-%x" % id(self))

    # -- the check -------------------------------------------------------
    def _materialize(self):
        """ONE stacked host read for all pending scalars, counted like
        every other host-blocking sync (`dl4j.pipeline.syncs`, site
        "guardian") so the zero-sync regression harness sees the
        guardian's true cadence."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.runtime import pipeline as _pipeline
        gnorms, oks, retryables = zip(*self._pending)
        self._pending = []
        t0 = time.perf_counter()
        flat = np.asarray(jnp.stack(
            [jnp.float32(g) for g in gnorms]
            + [jnp.float32(o) for o in oks]))
        if _mon.enabled():
            _pipeline.record_sync("guardian",
                                  (time.perf_counter() - t0) * 1e3)
        n = len(gnorms)
        return flat[:n], flat[n:] > 0.5, retryables

    def _flush(self):
        if self._action is not None and not self.driver_attached:
            # an action nothing consumed across a full step cycle and no
            # driver attached means there IS no driver (bare fit) — drop
            # it so the ladder keeps climbing toward DivergenceError
            # instead of freezing, and so health reports recover. The
            # rung's side effect (reduced LR / burned rollback budget)
            # stands. With a driver attached the action PERSISTS: the
            # driver consumes only after the whole batch, and mid-batch
            # flushes (TBPTT segments) must not eat its escalations.
            self._action = None
        self._climbed_this_flush = False
        first_step = self.step - len(self._pending) + 1
        gnorms, oks, retryables = self._materialize()
        self.checks += 1
        # a RETRY re-runs the NEWEST batch (the one the driver just
        # trained), so it is only legal when THAT step's update was
        # refused on device (params still pre-batch) and its batch may
        # be re-run (retryable; TBPTT segments are not) and the flush is
        # step-aligned. Which step climbed the rung doesn't matter —
        # the re-run target is always the newest.
        can_retry = (bool(retryables[-1]) and not bool(oks[-1])
                     and self._in_step_flush)
        any_bad = False
        for i, (g, ok, retryable) in enumerate(
                zip(gnorms, oks, retryables)):
            step_no = first_step + i
            spike = (self._ema is not None
                     and self._ema_n >= self.warmup_steps
                     and g > self.spike_factor * self._ema)
            if ok and not spike:
                if self._ema is None:
                    self._ema = float(g)
                else:
                    self._ema = (self.ema_decay * self._ema
                                 + (1.0 - self.ema_decay) * float(g))
                self._ema_n += 1
                self.last_good_step = step_no
                self._bad_streak = 0
                continue
            any_bad = True
            self._bad_streak += 1
            # device_refused: the guarded step's jnp.where never applied
            # this update. A host-only spike detection (ok but over the
            # EMA threshold the device had not learned yet) means the
            # update DID land — escalation may still reduce LR or roll
            # back, but re-running the batch would apply it twice.
            if not ok:
                self.skipped += 1
                if _mon.enabled():
                    _mon.get_registry().counter(
                        _mon.GUARDIAN_SKIPPED_UPDATES,
                        help="updates the guarded step refused to apply "
                             "(non-finite / grad spike)").inc()
            self._escalate(can_retry=can_retry)
        # feed the EMA threshold back to the device for upcoming steps
        if self._ema is not None and self._ema_n >= self.warmup_steps:
            self.max_gnorm = self.spike_factor * self._ema
        if any_bad:
            self._good_checks = 0
        else:
            self._good_checks += 1
            if self._good_checks >= self.recovery_checks \
                    and self.lr_scale != 1.0:
                self.lr_scale = 1.0
                self.lr_retries = 0
                if _mon.enabled():
                    _events.emit(
                        "resilience", _events.GUARDIAN_RECOVERED,
                        attrs={"step": self.step,
                               "good_checks": self._good_checks},
                        correlation_id="guardian-%x" % id(self))
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GUARDIAN_CHECKS,
                        help="guardian health checks performed").inc()
            reg.gauge(_mon.GUARDIAN_LAST_GOOD_STEP,
                      help="most recent healthy guarded step") \
               .set(self.last_good_step)
        if self.raise_on_divergence and not self.healthy:
            raise DivergenceError(
                f"training diverged: {self.skipped} skipped updates, "
                f"{self.lr_retries} LR retries (lr_scale="
                f"{self.lr_scale:.3g}), {self.rollbacks} rollbacks — "
                f"escalation ladder exhausted at step {self.step} "
                f"(last good step {self.last_good_step})")

    def _escalate(self, can_retry=True):
        """One unhealthy step: climb the ladder. The skip rung is
        implicit (the device never applied the update); deeper rungs set
        `_action` for the driver and/or flip `healthy`. can_retry=False
        still climbs the LR rung (the reduced lr_scale applies from the
        next step) but never asks the driver to re-run the batch —
        that would double-apply an update that already landed (host-side
        spike detections, stale flushes) or replay a batch whose healthy
        TBPTT segments already trained (retryable=False)."""
        if self._action is not None or self._climbed_this_flush:
            # one rung per flush window, and none while an action awaits
            # the driver — a check_every>1 window of bad steps must not
            # exhaust the whole ladder internally before the driver
            # could act on a single rung
            return
        if self._bad_streak <= self.max_skips:
            return                               # rung 1: skip-and-count
        self._climbed_this_flush = True
        if self.lr_retries < self.max_lr_retries:
            self.lr_scale *= self.lr_backoff     # rung 2: reduce LR,
            self.lr_retries += 1                 # ask for a batch retry
            self._bad_streak = 0
            if can_retry:
                self._action = RETRY
            if _mon.enabled():
                _mon.get_registry().counter(
                    _mon.GUARDIAN_LR_RETRIES,
                    help="reduce-LR-and-retry escalations").inc()
                _events.emit(
                    "resilience", _events.GUARDIAN_RETRY,
                    attrs={"step": self.step, "lr_scale": self.lr_scale,
                           "retry": bool(can_retry)},
                    correlation_id="guardian-%x" % id(self))
            return
        if self.rollbacks < self.max_rollbacks:
            self.rollbacks += 1                  # rung 3: checkpoint
            self._bad_streak = 0                 # rollback (driver acts)
            self._action = ROLLBACK
            if _mon.enabled():
                _mon.get_registry().counter(
                    _mon.GUARDIAN_ROLLBACKS,
                    help="checkpoint rollbacks the guardian "
                         "requested").inc()
                _events.emit(
                    "resilience", _events.GUARDIAN_ROLLBACK,
                    attrs={"step": self.step, "phase": "requested",
                           "rollbacks": self.rollbacks},
                    correlation_id="guardian-%x" % id(self))
            return
        self.healthy = False                     # rung 4: give up
        if _mon.enabled():
            _events.emit(
                "resilience", _events.GUARDIAN_DIVERGED,
                attrs={"step": self.step, "skipped": self.skipped,
                       "rollbacks": self.rollbacks},
                correlation_id="guardian-%x" % id(self))

    # -- introspection (GET /health) -------------------------------------
    def snapshot(self):
        status = "diverged" if not self.healthy else (
            "degraded" if (self._bad_streak or self.lr_scale != 1.0
                           or self._action is not None) else "ok")
        return {
            "status": status,
            "step": self.step,
            "last_good_step": self.last_good_step,
            "checks": self.checks,
            "skipped_updates": self.skipped,
            "lr_scale": self.lr_scale,
            "lr_retries": self.lr_retries,
            "rollbacks": self.rollbacks,
            "last_restored_step": self.last_restored_step,
            "grad_norm_ema": self._ema,
            "max_gnorm": (None if self.max_gnorm == float("inf")
                          else self.max_gnorm),
            "pending": len(self._pending),
        }


def clear_guardian():
    """Force-reset the global switch, ignoring any shadow chain — test
    teardown and emergency use only; running code pairs install() with
    uninstall() (or the with-statement)."""
    global ACTIVE
    ACTIVE = None
