"""Typed errors for the resilience subsystem.

Kept dependency-free (no jax, no package imports) so any layer —
`parallel/inference.py`, the trainers, user code — can import them
without cycles. Each error names the degradation mode it represents,
mirroring how the reference's SharedTrainingMaster surfaces distinct
failure classes (worker loss vs. transport backpressure) instead of one
opaque RuntimeError.
"""
from __future__ import annotations

__all__ = [
    "ResilienceError", "TransientError", "RetryExhaustedError",
    "CircuitOpenError", "InferenceTimeoutError",
    "InferenceOverloadedError", "InjectedFault", "FatalTrainingError",
    "DivergenceError", "CheckpointIntegrityError",
    "DistributedInitError", "PeerLostError", "PeerDesyncError",
    "PreemptionSignal", "ServerDeadError", "FleetDeadError",
    "MemoryPressureError", "PagePoolExhaustedError",
    "ReplayDivergedError", "WireFormatError", "MembershipChangeError",
]


class ResilienceError(RuntimeError):
    """Base class for every typed resilience error."""


class TransientError(ResilienceError):
    """An error the raiser asserts is safe to retry (device hiccup,
    preempted dispatch, transport blip). `RetryPolicy` always classifies
    this type as retryable."""


class RetryExhaustedError(ResilienceError):
    """RetryPolicy gave up: attempt budget or deadline exceeded. The
    last underlying failure rides along as `__cause__` / `.last_error`."""

    def __init__(self, message, last_error=None, attempts=0):
        super().__init__(message)
        self.last_error = last_error
        self.attempts = attempts


class CircuitOpenError(ResilienceError):
    """The circuit breaker is OPEN: calls are shed without being tried
    until the cooldown elapses (then one half-open probe is allowed)."""


class InferenceTimeoutError(ResilienceError):
    """A ParallelInference request missed its per-request deadline
    (`output(x, timeout_ms=...)`). The request is cancelled: a late
    result, if one arrives, is discarded."""


class InferenceOverloadedError(ResilienceError):
    """ParallelInference shed the request because the queue stayed full
    for the whole bounded enqueue wait — graceful degradation instead of
    blocking the caller indefinitely."""


class InjectedFault(TransientError):
    """Default exception raised by the fault-injection harness
    (`resilience/faults.py`). Transient by definition, so retry paths
    exercise their backoff logic under injection."""


class FatalTrainingError(ResilienceError):
    """A deliberately NON-retryable injected/classified failure — used by
    fault plans to simulate a process kill (the trainer must crash and
    later resume from its checkpoint, not retry through it)."""


class DivergenceError(ResilienceError):
    """The training guardian exhausted its escalation ladder
    (skip-and-count → reduced-LR retry → checkpoint rollback) and the
    run is still producing non-finite losses or grad-norm spikes.
    Deliberately non-retryable: retrying a diverged run just re-diverges
    — the fix is data/LR/config, and the model still holds the
    last-known-good (rolled-back) parameters for a post-mortem."""


class CheckpointIntegrityError(ResilienceError):
    """A checkpoint failed manifest verification on restore (checksum /
    structure mismatch, non-finite params, or a truncated write) and no
    older generation could be restored either."""


class DistributedInitError(ResilienceError):
    """Multi-host bootstrap failed LOUDLY: the coordinator never came up
    within the connect deadline, the post-init sanity barrier timed out,
    or the cluster shape (process count / device count) does not match
    what every peer expected. Deliberately typed so supervisors can tell
    'the cluster never formed' (re-schedule the whole job) from a
    mid-run peer loss (`PeerLostError`, restart one worker)."""


class PeerLostError(ResilienceError):
    """A peer process stopped heartbeating / never reached a barrier
    within the configured timeout — it was killed, wedged inside a
    collective, or partitioned. Raised on the SURVIVING host within a
    bounded time instead of hanging in the next collective forever; a
    peer-table dump is written first (`.report_path` when available)."""

    def __init__(self, message, peers=None, report_path=None):
        super().__init__(message)
        #: peer-table snapshot at detection time (pid -> info dict)
        self.peers = peers or {}
        self.report_path = report_path


class PeerDesyncError(PeerLostError):
    """Peers are alive but NOT on the same step / control decision — the
    lockstep SPMD contract is broken (e.g. one worker skipped a batch
    the others trained). Continuing would silently corrupt the model, so
    the step-agreement check fails the run instead."""


class ServerDeadError(ResilienceError):
    """A serving loop (the GenerationServer decode thread) exhausted
    its supervised-restart budget and is permanently down: every
    in-flight, replay-pending, and queued request was failed with this
    error and future submits refuse immediately. Deliberately typed so
    a fleet supervisor can tell 'replace this replica' from a transient
    per-request failure; `GET /health` reports `serving_dead`."""


class FleetDeadError(ServerDeadError):
    """Every replica behind a FleetRouter is dead and replacement is
    exhausted (or disabled): the fleet as a whole can no longer serve.
    Latched exactly like the per-replica ServerDeadError — every open
    fleet request fails with this error and future submits refuse
    immediately. Deliberately a ServerDeadError subclass so callers
    handling 'serving is down' catch both; the distinct type tells an
    operator the outage is fleet-wide, not one replaceable replica."""


class MemoryPressureError(ResilienceError):
    """The serving memory-pressure degradation ladder refused work
    instead of risking (or after observing) a device OOM: a cache
    growth past the capped rung, a queued admission shed while under
    pressure, or an in-flight request that no longer fits the shrunken
    cache rung. The server itself stays up — only the refused request
    fails."""


class PagePoolExhaustedError(MemoryPressureError):
    """The paged KV allocator ran out of physical pages even after
    evicting every cold (refcount-zero) shared page. At admission the
    request is refused typed and the server keeps serving; mid-stream
    the error carries the RESOURCE_EXHAUSTED token so the OOM
    classifier routes it through the degradation ladder (shed →
    evict-cold-pages → shrink) and crash-replay re-packs the pool from
    the journal."""

    def __init__(self, message):
        super().__init__(f"{message} (RESOURCE_EXHAUSTED: kv page pool)")


class ReplayDivergedError(ResilienceError):
    """Crash-replay re-generated a token that does not match the
    journaled (already-delivered) stream — the per-slot-key purity
    contract was violated (should never happen; a bug or nondeterminism
    in the decode path). The affected request fails typed rather than
    silently delivering a forked continuation."""


class WireFormatError(ResilienceError):
    """A sparse gradient wire message failed structural validation
    (truncated payload, count/token mismatch, non-finite threshold, or an
    out-of-range token index). The in-jit decode path poisons the
    delivered gradient to NaN so the guardian gates the step — this typed
    error is what the host-side validator (`compression.check_payload`)
    and the `wire.decode` fault site raise, so corruption is contained
    loudly, never delivered as a silent wrong gradient."""


class MembershipChangeError(ResilienceError):
    """An elastic membership transition (join admission, leave, or
    replacement re-form) failed before it could commit: the joiner died
    mid-admission, the reform barrier timed out, or the roster write was
    lost. The previous membership epoch stays authoritative — survivors
    keep training on the old roster and the transition is retried or
    abandoned, never half-applied."""


class PreemptionSignal(ResilienceError):
    """A preemption notice (SIGTERM, or the `host.preempt` injection
    site): the process must drain the in-flight step, write a final
    coordinated checkpoint, and exit cleanly. Raised to UNWIND the fit
    loop after the drain — it means 'shut down now', not 'something
    broke'; `resume_or_init` on restart continues bit-identically."""

    def __init__(self, message="preempted", step=None):
        super().__init__(message)
        self.step = step
