"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of the
Eclipse Deeplearning4j monorepo (reference: grzegorzgajda/deeplearning4j):
ND4J-style arrays (`ops`), the NeuralNetConfiguration builder DSL +
MultiLayerNetwork / ComputationGraph (`nn`), a SameDiff-equivalent graph
engine (`autodiff`), zoo models (`models`), distributed training over
`jax.sharding.Mesh` (`parallel`), data pipelines (`datasets`, `datavec`,
native C++ in `runtime`), evaluation (`eval`), and aux subsystems
(transfer learning, NLP, RL, hyperparameter search, UI stats).

Design notes: everything on the compute path is pure-functional and
jit-compiled as whole training steps (one XLA executable per step, donated
buffers); distribution is sharding annotations + compiler-inserted
collectives over ICI/DCN, not explicit messaging.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.ops import nd  # noqa: F401
