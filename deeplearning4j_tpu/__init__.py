"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of the
Eclipse Deeplearning4j monorepo (reference: grzegorzgajda/deeplearning4j):
ND4J-style arrays (`ops`), the NeuralNetConfiguration builder DSL +
MultiLayerNetwork / ComputationGraph (`nn`), a SameDiff-equivalent graph
engine (`autodiff`), zoo models (`models`), distributed training over
`jax.sharding.Mesh` (`parallel`), data pipelines (`datasets`, `datavec`,
native C++ in `runtime`), evaluation (`eval`), and aux subsystems
(transfer learning, NLP, RL, hyperparameter search, UI stats).

Design notes: everything on the compute path is pure-functional and
jit-compiled as whole training steps (one XLA executable per step, donated
buffers); distribution is sharding annotations + compiler-inserted
collectives over ICI/DCN, not explicit messaging.
"""

__version__ = "0.1.0"


def _tpu_attach_guard():
    """Make TPU attachment EXPLICIT (opt-in), never accidental.

    This container's sitecustomize registers the axon TPU PJRT plugin in
    every python process and presets JAX_PLATFORMS=axon, so any script
    importing this package would silently attach to the tunnelled TPU.
    Killing such a process mid-RPC wedges the tunnel for hours (BENCH.md
    outage log, rounds 3+4) — and "a CPU-side helper script forgot the env
    scrub" has now caused a multi-hour outage twice. Defense in depth:
    unless the process asserts `DL4J_TPU_WANT_TPU=1` *before* importing
    this package (bench.py and __graft_entry__.entry do), importing the
    framework pins jax to the CPU backend. jax.config.update applied
    before any backend initialization reliably overrides the plugin's
    platform hook (the same mechanism __graft_entry__.dryrun_multichip has
    used since round 2); if a backend is already live we leave it alone —
    the importer already owns its platform choice.
    """
    import os

    if os.environ.get("DL4J_TPU_WANT_TPU") == "1":
        return
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return  # no tunnelled-TPU plugin in this environment
    import sys

    import jax

    global _CPU_PINNED, _GUARD_PREV_PLATFORMS
    try:
        _GUARD_PREV_PLATFORMS = jax.config.jax_platforms
        jax.config.update("jax_platforms", "cpu")
        _CPU_PINNED = True
        print("deeplearning4j_tpu: axon TPU plugin detected but "
              "DL4J_TPU_WANT_TPU!=1 — pinning this process to CPU "
              "(set DL4J_TPU_WANT_TPU=1 before import, or call "
              "unpin_cpu(), for the chip)",
              file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001
        # A dead guard reopens the attach-and-wedge hazard — never die
        # silent. Expected cause: a jax backend initialized before this
        # import (the importer owns its platform); anything else (e.g. a
        # renamed config option after a jax upgrade) needs investigating.
        print("deeplearning4j_tpu: TPU attach guard could NOT pin CPU "
              f"({type(e).__name__}: {e}) — if no jax backend was "
              "initialized before this import, the guard is broken and "
              "this process may attach to the tunnelled TPU",
              file=sys.stderr, flush=True)


#: True when the attach guard redirected this process to CPU; the platform
#: value it displaced is kept so unpin_cpu() can restore it.
_CPU_PINNED = False
_GUARD_PREV_PLATFORMS = None


def unpin_cpu():
    """Undo the attach guard's CPU pin for a legitimate TPU consumer that
    imported the package before declaring DL4J_TPU_WANT_TPU=1 (e.g. the
    driver importing __graft_entry__ ahead of calling entry()). Returns
    True if the process can now see the TPU platform, False if a backend
    was already initialized on CPU (too late — set the env var before the
    first import instead)."""
    global _CPU_PINNED
    if not _CPU_PINNED:
        return True
    import jax

    try:
        jax.config.update("jax_platforms", _GUARD_PREV_PLATFORMS)
        _CPU_PINNED = False
        return True
    except Exception:  # noqa: BLE001 — backend already initialized
        return False


_tpu_attach_guard()

from deeplearning4j_tpu.ops import nd  # noqa: F401, E402
