"""Keras model import (≡ deeplearning4j-modelimport)."""
from deeplearning4j_tpu.keras_import.keras_import import (
    InvalidKerasConfigurationException, KerasModelImport, clearLambdas,
    registerCustomLayer, registerLambda)

__all__ = ["InvalidKerasConfigurationException", "KerasModelImport",
           "registerCustomLayer", "registerLambda", "clearLambdas"]
