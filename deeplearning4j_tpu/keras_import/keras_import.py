"""Keras model import (≡ deeplearning4j-modelimport ::
org.deeplearning4j.nn.modelimport.keras.KerasModelImport,
KerasSequentialModel, KerasModel).

Parses Keras JSON configs (Sequential and Functional) into the native
builder DSL — the import path produces the SAME MultiLayerConfiguration /
ComputationGraphConfiguration a user would write by hand, so imported
models get the identical jitted train/inference path. Weights load from
Keras .h5 files via h5py (present in this environment); layouts match
natively (NHWC conv kernels are HWIO in both stacks — no OIHW transpose
dance like the reference's KerasConvolutionUtils).
"""
from __future__ import annotations

import json
import os

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (ElementWiseVertex,
                                                       MergeVertex)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               Convolution1DLayer,
                                               ConvolutionLayer, Cropping2D,
                                               DenseLayer,
                                               DepthwiseConvolution2D,
                                               DropoutLayer, EmbeddingLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer, PReLULayer,
                                               SeparableConvolution2D,
                                               Subsampling1DLayer,
                                               SubsamplingLayer, Upsampling2D,
                                               ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer, SimpleRnn
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "sigmoid": "sigmoid", "tanh": "tanh", "softmax": "softmax",
    "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid",
    "swish": "swish", "silu": "swish", "gelu": "gelu",
    "leaky_relu": "leakyrelu", "exponential": "exp", "mish": "mish",
}

_INITIALIZERS = {
    "GlorotUniform": "xavier_uniform", "glorot_uniform": "xavier_uniform",
    "GlorotNormal": "xavier", "glorot_normal": "xavier",
    "HeNormal": "relu", "he_normal": "relu",
    "HeUniform": "relu_uniform", "he_uniform": "relu_uniform",
    "LecunNormal": "lecun", "lecun_normal": "lecun",
    "LecunUniform": "lecun_uniform", "lecun_uniform": "lecun_uniform",
    "RandomNormal": "normal", "random_normal": "normal",
    "RandomUniform": "uniform", "random_uniform": "uniform",
    "Zeros": "zero", "zeros": "zero", "Ones": "ones", "ones": "ones",
}


class InvalidKerasConfigurationException(ValueError):
    """≡ modelimport.keras.exceptions.InvalidKerasConfigurationException."""


def _map_activation(name):
    if name is None:
        return "identity"
    act = _ACTIVATIONS.get(name)
    if act is None:
        raise InvalidKerasConfigurationException(
            f"Unsupported Keras activation: {name!r}")
    return act


def _map_init(cfg):
    if not cfg:
        return "xavier_uniform"
    name = cfg.get("class_name", cfg) if isinstance(cfg, dict) else cfg
    return _INITIALIZERS.get(name, "xavier_uniform")


def _loss_for_activation(act):
    """No training_config in a bare architecture JSON → pick the loss the
    reference's enforceTrainingConfig=false path would allow fine-tuning
    with: softmax→MCXENT, sigmoid→XENT, else MSE."""
    return {"softmax": "mcxent", "sigmoid": "xent"}.get(act, "mse")


def _keras_input_type(batch_shape):
    dims = [d for d in batch_shape[1:]]
    if len(dims) == 4:
        # volumetric NDHWC (channels_last, like all our conv layouts)
        return InputType.convolutional3D(dims[0], dims[1], dims[2], dims[3])
    if len(dims) == 3:
        return InputType.convolutional(dims[0], dims[1], dims[2])
    if len(dims) == 2:
        # keep the static sequence length when keras declares one —
        # length-dependent layers (LocallyConnected1D) need it
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 1:
        return InputType.feedForward(dims[0])
    raise InvalidKerasConfigurationException(
        f"Unsupported input shape: {batch_shape}")


#: custom Keras layer converters (≡ KerasLayer.registerCustomLayer): maps a
#: Keras class_name to a callable (config_dict, is_last) -> Layer — the hook
#: user-defined SameDiffLayer subclasses ride in on
_CUSTOM_LAYER_CONVERTERS = {}


def registerCustomLayer(class_name, converter):
    """Register a converter for an unsupported Keras layer type.
    `converter(cfg: dict, is_last: bool) -> Layer` (typically returning a
    user SameDiffLayer subclass from nn.conf.samediff_layers)."""
    if not callable(converter):
        raise TypeError("converter must be callable: (cfg, is_last) -> Layer")
    _CUSTOM_LAYER_CONVERTERS[str(class_name)] = converter


def clearCustomLayers():
    _CUSTOM_LAYER_CONVERTERS.clear()


#: Lambda implementations by Keras layer NAME (≡ modelimport.keras ::
#: KerasLambda): Keras JSON stores only marshaled Python for Lambda
#: layers, so the reference requires the user to register the
#: implementation before import — same contract here.
_LAMBDA_IMPLS = {}


def registerLambda(layer_name, fn):
    """Register the implementation for a Keras Lambda layer by its layer
    name: fn(x) -> array (a pure jax function). Must be called before
    importing a model whose JSON contains that Lambda."""
    if not callable(fn):
        raise TypeError("fn must be a callable (pure jax function)")
    _LAMBDA_IMPLS[str(layer_name)] = fn


def clearLambdas():
    _LAMBDA_IMPLS.clear()


def _convert_layer(class_name, cfg, is_last=False):
    """One Keras layer config → our layer instance (or None to skip)."""
    if class_name in _CUSTOM_LAYER_CONVERTERS:
        return _CUSTOM_LAYER_CONVERTERS[class_name](cfg, is_last)
    act = _map_activation(cfg.get("activation", "linear"))
    init = _map_init(cfg.get("kernel_initializer"))
    bias = cfg.get("use_bias", True)

    if class_name == "Dense":
        if is_last:
            return OutputLayer(nOut=cfg["units"], activation=act,
                               lossFunction=_loss_for_activation(act),
                               weightInit=init, hasBias=bias)
        return DenseLayer(nOut=cfg["units"], activation=act,
                          weightInit=init, hasBias=bias)
    if class_name in ("Conv2D", "Convolution2D"):
        return ConvolutionLayer(
            nOut=cfg["filters"], kernelSize=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1))),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name == "SeparableConv2D":
        return SeparableConvolution2D(
            nOut=cfg["filters"], kernelSize=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1))),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pool = "max" if class_name.startswith("Max") else "avg"
        size = tuple(cfg.get("pool_size", (2, 2)))
        return SubsamplingLayer(
            poolingType=pool, kernelSize=size,
            stride=tuple(cfg.get("strides") or size),
            convolutionMode=cfg.get("padding", "valid"))
    if class_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D",
                      "GlobalAveragePooling1D", "GlobalMaxPooling1D"):
        return GlobalPoolingLayer(
            poolingType="avg" if "Average" in class_name else "max")
    if class_name == "BatchNormalization":
        return BatchNormalization(eps=cfg.get("epsilon", 1e-3),
                                  decay=cfg.get("momentum", 0.99))
    if class_name == "Dropout":
        return DropoutLayer(dropOut=1.0 - float(cfg.get("rate", 0.5)))
    if class_name == "Activation":
        return ActivationLayer(activation=act)
    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", 1)
        return ZeroPaddingLayer(padding=pad)
    if class_name == "UpSampling2D":
        size = cfg.get("size", (2, 2))
        return Upsampling2D(size=size[0] if isinstance(
            size, (list, tuple)) else size)
    if class_name == "Embedding":
        return EmbeddingLayer(nIn=cfg["input_dim"], nOut=cfg["output_dim"])
    if class_name == "LSTM":
        if is_last:
            return RnnOutputLayer(nOut=cfg["units"], activation=act,
                                  lossFunction=_loss_for_activation(act))
        return LSTM(nOut=cfg["units"], activation=act,
                    gateActivationFn=_map_activation(
                        cfg.get("recurrent_activation", "sigmoid")),
                    weightInit=init)
    if class_name == "SimpleRNN":
        return SimpleRnn(nOut=cfg["units"], activation=act, weightInit=init)
    if class_name == "DepthwiseConv2D":
        # keras spells the initializer 'depthwise_initializer' here
        dw_init = _map_init(cfg.get("depthwise_initializer")
                            or cfg.get("kernel_initializer"))
        return DepthwiseConvolution2D(
            depthMultiplier=int(cfg.get("depth_multiplier", 1)),
            kernelSize=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1))),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=dw_init, hasBias=bias)
    if class_name == "Cropping2D":
        return Cropping2D(cropping=cfg.get("cropping", ((0, 0), (0, 0))))
    if class_name == "UpSampling1D":
        from deeplearning4j_tpu.nn.conf.layers import Upsampling1D
        return Upsampling1D(size=int(cfg.get("size", 2)))
    if class_name == "TimeDistributed":
        # our Dense/Output layers already broadcast over (B, T, F); unwrap
        # the inner layer (≡ KerasTimeDistributed flattening to the wrapped
        # layer with RNN format preserved)
        inner = cfg.get("layer") or {}
        return _convert_layer(inner.get("class_name"),
                              inner.get("config", {}), is_last=is_last)
    if class_name in ("SpatialDropout2D", "SpatialDropout1D"):
        # real channel-wise dropout (≡ KerasSpatialDropout): whole feature
        # maps drop together; keras rate = drop prob, ours = retain
        from deeplearning4j_tpu.nn.dropout import SpatialDropout
        return DropoutLayer(
            dropOut=SpatialDropout(1.0 - float(cfg.get("rate", 0.5))))
    if class_name == "LocallyConnected2D":
        from deeplearning4j_tpu.nn.conf.special_layers import \
            LocallyConnected2D
        return LocallyConnected2D(
            nOut=cfg["filters"], kernelSize=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1))),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name == "LocallyConnected1D":
        from deeplearning4j_tpu.nn.conf.special_layers import \
            LocallyConnected1D
        ks = cfg["kernel_size"]
        st = cfg.get("strides", 1)
        return LocallyConnected1D(
            nOut=cfg["filters"],
            kernelSize=ks[0] if isinstance(ks, (list, tuple)) else ks,
            stride=st[0] if isinstance(st, (list, tuple)) else st,
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name == "Permute":
        from deeplearning4j_tpu.nn.conf.special_layers import PermuteLayer
        return PermuteLayer(dims=tuple(cfg["dims"]))
    if class_name == "Lambda":
        fn = _LAMBDA_IMPLS.get(cfg.get("name"))
        if fn is None:
            raise InvalidKerasConfigurationException(
                f"Lambda layer {cfg.get('name')!r}: Keras JSON stores only "
                "marshaled Python for Lambda layers, so the implementation "
                "must be supplied at import time — call "
                "registerLambda(name, fn) first (≡ the reference's "
                "KerasLambda contract), or registerCustomLayer('Lambda', "
                "converter) for full control")
        from deeplearning4j_tpu.nn.conf.samediff_layers import \
            SameDiffLambdaLayer
        return SameDiffLambdaLayer(fn=fn)
    if class_name == "Bidirectional":
        inner_cfg = cfg.get("layer") or {}
        inner = _convert_layer(inner_cfg.get("class_name"),
                               inner_cfg.get("config", {}))
        from deeplearning4j_tpu.nn.conf.recurrent import Bidirectional
        mm = cfg.get("merge_mode", "concat")
        modes = {"concat": "concat", "sum": "add", "ave": "average",
                 "mul": "mul"}
        if mm not in modes:
            # merge_mode=None returns TWO sequences in Keras — structurally
            # different; refuse rather than silently concat
            raise InvalidKerasConfigurationException(
                f"Bidirectional merge_mode={mm!r} unsupported (use "
                "concat/sum/ave/mul)")
        return Bidirectional(layer=inner, mode=modes[mm])
    if class_name == "Conv1D":
        return Convolution1DLayer(
            nOut=cfg["filters"],
            kernelSize=(cfg["kernel_size"][0]
                        if isinstance(cfg.get("kernel_size"), (list, tuple))
                        else cfg.get("kernel_size", 3)),
            stride=(cfg.get("strides", [1])[0]
                    if isinstance(cfg.get("strides"), (list, tuple))
                    else cfg.get("strides", 1)),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        pool = "max" if class_name.startswith("Max") else "avg"
        size = cfg.get("pool_size", 2)
        size = size[0] if isinstance(size, (list, tuple)) else size
        stride = cfg.get("strides") or size
        stride = stride[0] if isinstance(stride, (list, tuple)) else stride
        return Subsampling1DLayer(poolingType=pool, kernelSize=int(size),
                                  stride=int(stride),
                                  convolutionMode=cfg.get("padding", "valid"))
    if class_name in ("Conv2DTranspose", "Conv3DTranspose"):
        # refuse silently-shape-changing options rather than approximate
        # (same policy as Bidirectional merge_mode=None)
        op = cfg.get("output_padding")
        if op is not None and any(int(v) != 0 for v in
                                  (op if isinstance(op, (list, tuple))
                                   else [op])):
            raise InvalidKerasConfigurationException(
                f"{class_name} output_padding={op!r} unsupported — output "
                "shape would silently differ from the source model")
        dil = cfg.get("dilation_rate", 1)
        if any(int(v) != 1 for v in
               (dil if isinstance(dil, (list, tuple)) else [dil])):
            raise InvalidKerasConfigurationException(
                f"{class_name} dilation_rate={dil!r} unsupported")
        if class_name == "Conv2DTranspose":
            from deeplearning4j_tpu.nn.conf.layers import Deconvolution2D
            return Deconvolution2D(
                nOut=cfg["filters"], kernelSize=tuple(cfg["kernel_size"]),
                stride=tuple(cfg.get("strides", (1, 1))),
                convolutionMode=cfg.get("padding", "valid"),
                activation=act, weightInit=init, hasBias=bias)
        from deeplearning4j_tpu.nn.conf.layers3d import Deconvolution3D
        return Deconvolution3D(
            nOut=cfg["filters"], kernelSize=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1, 1))),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name == "Conv3D":
        from deeplearning4j_tpu.nn.conf.layers3d import Convolution3D
        return Convolution3D(
            nOut=cfg["filters"], kernelSize=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1, 1))),
            convolutionMode=cfg.get("padding", "valid"),
            activation=act, weightInit=init, hasBias=bias)
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        from deeplearning4j_tpu.nn.conf.layers3d import Subsampling3DLayer
        pool = "max" if class_name.startswith("Max") else "avg"
        size = tuple(cfg.get("pool_size", (2, 2, 2)))
        return Subsampling3DLayer(
            poolingType=pool, kernelSize=size,
            stride=tuple(cfg.get("strides") or size),
            convolutionMode=cfg.get("padding", "valid"))
    if class_name == "UpSampling3D":
        from deeplearning4j_tpu.nn.conf.layers3d import Upsampling3D
        return Upsampling3D(size=tuple(cfg.get("size", (2, 2, 2))))
    if class_name == "ZeroPadding3D":
        # the layer constructor normalizes all three Keras spellings
        from deeplearning4j_tpu.nn.conf.layers3d import ZeroPadding3DLayer
        return ZeroPadding3DLayer(padding=cfg.get("padding", 1))
    if class_name == "Cropping3D":
        from deeplearning4j_tpu.nn.conf.layers3d import Cropping3D
        return Cropping3D(cropping=cfg.get("cropping", 0))
    if class_name == "LeakyReLU":
        # Keras default alpha is 0.3 (ours is 0.01) — carry it explicitly
        alpha = float(cfg.get("alpha", 0.3))
        return ActivationLayer(activation=f"leakyrelu:{alpha}")
    if class_name == "ELU":
        return ActivationLayer(activation="elu")
    if class_name == "ThresholdedReLU":
        return ActivationLayer(
            activation=f"thresholdedrelu:{float(cfg.get('theta', 1.0))}")
    if class_name == "ReLU":
        mv = cfg.get("max_value")
        neg = float(cfg.get("negative_slope", 0.0) or 0.0)
        thr = float(cfg.get("threshold", 0.0) or 0.0)
        if thr != 0.0 or (mv is not None and neg != 0.0):
            raise InvalidKerasConfigurationException(
                f"ReLU(max_value={mv}, negative_slope={neg}, "
                f"threshold={thr}) has no exact equivalent here")
        if neg != 0.0:
            return ActivationLayer(activation=f"leakyrelu:{neg}")
        if mv is not None:
            return ActivationLayer(activation=f"relucap:{float(mv)}")
        return ActivationLayer(activation="relu")
    if class_name == "PReLU":
        return PReLULayer()
    if class_name == "GaussianDropout":
        from deeplearning4j_tpu.nn.dropout import GaussianDropout
        return DropoutLayer(dropOut=GaussianDropout(
            float(cfg.get("rate", 0.5))))
    if class_name == "GaussianNoise":
        from deeplearning4j_tpu.nn.dropout import GaussianNoise
        return DropoutLayer(dropOut=GaussianNoise(
            float(cfg.get("stddev", 0.1))))
    if class_name in ("Flatten", "Reshape", "InputLayer"):
        return None  # shape plumbing — the builder's InputType inference
    raise InvalidKerasConfigurationException(
        f"Unsupported Keras layer: {class_name}")


def _load_json(path_or_json):
    if isinstance(path_or_json, dict):
        return path_or_json
    s = str(path_or_json)
    if os.path.exists(s):
        with open(s) as f:
            return json.load(f)
    return json.loads(s)


class KerasModelImport:
    @staticmethod
    def importKerasSequentialConfiguration(path_or_json, inputType=None):
        """Sequential architecture JSON → MultiLayerConfiguration."""
        model = _load_json(path_or_json)
        if model.get("class_name") != "Sequential":
            raise InvalidKerasConfigurationException(
                f"Not a Sequential model: {model.get('class_name')}")
        layer_cfgs = model["config"]
        if isinstance(layer_cfgs, dict):
            layer_cfgs = layer_cfgs["layers"]
        b = NeuralNetConfiguration.Builder().list()
        converted = []
        pending_mask_value = None  # from a Keras Masking layer
        for i, lc in enumerate(layer_cfgs):
            cls, cfg = lc["class_name"], lc.get("config", {})
            if inputType is None and (
                    "batch_input_shape" in cfg or "batch_shape" in cfg):
                inputType = _keras_input_type(
                    cfg.get("batch_input_shape") or cfg["batch_shape"])
            if cls == "Masking":
                # Keras Masking derives the time mask from in-band padding
                # and propagates it to downstream RNNs — our equivalent
                # wraps the NEXT recurrent layer in MaskZeroLayer
                pending_mask_value = float(cfg.get("mask_value", 0.0))
                continue
            layer = _convert_layer(cls, cfg,
                                   is_last=(i == len(layer_cfgs) - 1))
            if layer is None and pending_mask_value is not None:
                # Flatten/Reshape between Masking and the RNN would change
                # which values the derived mask keys off — refuse
                raise InvalidKerasConfigurationException(
                    "Masking must be immediately followed by a recurrent "
                    f"layer; found {cls}")
            if layer is not None:
                if pending_mask_value is not None:
                    # Masking must feed DIRECTLY into a recurrent layer —
                    # any intervening transform would change the in-band
                    # padding values the derived mask keys off
                    if not getattr(layer, "is_recurrent", False):
                        raise InvalidKerasConfigurationException(
                            "Masking must be immediately followed by a "
                            f"recurrent layer; found {cls}")
                    from deeplearning4j_tpu.nn.conf.sequence_layers import \
                        MaskZeroLayer
                    layer = MaskZeroLayer(layer, pending_mask_value)
                    pending_mask_value = None
                layer.name = cfg.get("name", f"layer{i}")
                converted.append(layer)
                b.layer(layer)
        if pending_mask_value is not None:
            raise InvalidKerasConfigurationException(
                "Masking layer has no recurrent layer after it")
        if inputType is None:
            raise InvalidKerasConfigurationException(
                "No batch_input_shape in config; pass inputType=")
        return b.setInputType(inputType).build()

    @staticmethod
    def importKerasSequentialModelAndWeights(config_path, weights_path=None,
                                             inputType=None):
        conf = KerasModelImport.importKerasSequentialConfiguration(
            config_path, inputType)
        net = MultiLayerNetwork(conf).init()
        if weights_path is not None:
            _load_h5_weights_multilayer(net, weights_path)
        return net

    @staticmethod
    def importKerasModelConfiguration(path_or_json, inputTypes=None):
        """Functional-API JSON → ComputationGraphConfiguration."""
        model = _load_json(path_or_json)
        if model.get("class_name") not in ("Model", "Functional"):
            raise InvalidKerasConfigurationException(
                f"Not a functional model: {model.get('class_name')}")
        cfg = model["config"]
        g = NeuralNetConfiguration.Builder().graphBuilder()
        input_names, input_types = [], []
        layer_list = cfg["layers"]
        for lc in layer_list:
            cls, c, name = lc["class_name"], lc.get("config", {}), None
            name = c.get("name") or lc.get("name")
            inbound = _inbound_names(lc)
            if cls == "InputLayer":
                input_names.append(name)
                shape = c.get("batch_input_shape") or c.get("batch_shape")
                input_types.append(_keras_input_type(shape))
                continue
            is_output = any(name == (o[0] if isinstance(o, list) else o)
                            for o in _output_names(cfg))
            if cls in ("Add", "Subtract", "Multiply", "Average", "Maximum",
                       "Minimum"):
                op = {"Add": "add", "Subtract": "subtract",
                      "Multiply": "product", "Average": "average",
                      "Maximum": "max", "Minimum": "min"}[cls]
                g.addVertex(name, ElementWiseVertex(op), *inbound)
                continue
            if cls == "Concatenate":
                g.addVertex(name, MergeVertex(), *inbound)
                continue
            layer = _convert_layer(cls, c, is_last=is_output)
            if layer is None:
                if cls == "Flatten":
                    # real (B, ...) -> (B, prod) flatten: downstream
                    # layers must see a feed-forward type (a CNN input
                    # also rides the same reshape — NHWC order matches
                    # Keras channels_last)
                    g.addVertex(name, _FlattenVertex(), *inbound)
                else:   # Reshape/InputLayer: alias to input
                    g.addVertex(name, _IdentityAlias(), *inbound)
                continue
            g.addLayer(name, layer, *inbound)
        g.addInputs(*input_names)
        g.setInputTypes(*(inputTypes or input_types))
        g.setOutputs(*[o[0] if isinstance(o, list) else o
                       for o in _output_names(cfg)])
        return g.build()

    @staticmethod
    def importKerasModelAndWeights(config_path, weights_path=None,
                                   inputTypes=None):
        conf = KerasModelImport.importKerasModelConfiguration(
            config_path, inputTypes)
        net = ComputationGraph(conf).init()
        if weights_path is not None:
            _load_h5_weights_graph(net, weights_path)
        return net


def _inbound_names(layer_cfg):
    out = []
    for node in layer_cfg.get("inbound_nodes", []):
        if isinstance(node, dict):  # keras 3 style {"args": [...]}
            for a in node.get("args", []):
                out.extend(_extract_history(a))
        else:
            for ref in node:
                out.append(ref[0] if isinstance(ref, list) else ref)
    return out


def _extract_history(arg):
    if isinstance(arg, dict) and "config" in arg:
        kh = arg["config"].get("keras_history")
        if kh:
            return [kh[0]]
    if isinstance(arg, list):
        out = []
        for a in arg:
            out.extend(_extract_history(a))
        return out
    return []


def _output_names(cfg):
    outs = cfg.get("output_layers", [])
    return outs if isinstance(outs, list) else [outs]


class _IdentityAlias:
    """Pass-through vertex for Keras shape-only layers (Flatten/Reshape);
    our builder handles layout via input preprocessors."""

    def output_type(self, *input_types):
        return input_types[0]

    def apply(self, *xs, mask=None):
        return xs[0]

    def feed_forward_mask(self, *parent_masks):
        # the alias is a pure identity (Reshape/InputLayer): the tensor
        # and its time axis are unchanged, so the mask stays valid
        # (Flatten, which collapses the masked axis, has _FlattenVertex)
        return next((m for m in parent_masks if m is not None), None)


class _FlattenVertex:
    """Keras Flatten in the functional graph: (B, ...) -> (B, prod)."""

    def output_type(self, *input_types):
        import numpy as _np

        from deeplearning4j_tpu.nn.conf.inputs import InputType
        return InputType.feedForward(int(_np.prod(input_types[0].shape())))

    def apply(self, *xs, mask=None):
        x = xs[0]
        return x.reshape(x.shape[0], -1)

    def feed_forward_mask(self, *parent_masks):
        return None


# -- .h5 weight loading (gated on h5py, which this image ships) ----------
def _h5_layer_weights(weights_path):
    """layer name → [(dataset leaf name, array), ...] in save order.

    Leaf names are Keras's canonical weight names (kernel / bias / gamma /
    beta / moving_mean / moving_variance / recurrent_kernel / embeddings /
    depthwise_kernel / pointwise_kernel), with any ":0" suffix stripped —
    the reference's KerasLayer maps by these names, never by shape.
    """
    import h5py
    out = {}
    with h5py.File(weights_path, "r") as f:
        grp = f["model_weights"] if "model_weights" in f else f
        for lname in grp:
            sub = grp[lname]
            arrs = []

            def visit(path, obj):
                if hasattr(obj, "shape"):
                    leaf = path.split("/")[-1].split(":")[0]
                    # keep Bidirectional direction info: Keras nests the
                    # wrapped layers under forward_*/backward_* groups
                    if "forward" in path:
                        leaf = "forward/" + leaf
                    elif "backward" in path:
                        leaf = "backward/" + leaf
                    arrs.append((leaf, np.array(obj)))
            sub.visititems(visit)
            if arrs:
                out[lname] = arrs
    return out


# Keras weight dataset name → (our params key, our state key)
_KERAS_WEIGHT_NAMES = {
    "kernel": ("W", None),
    "embeddings": ("W", None),
    "recurrent_kernel": ("U", None),
    "bias": ("b", None),
    "gamma": ("gamma", None),
    "beta": ("beta", None),
    "moving_mean": (None, "mean"),
    "moving_variance": (None, "var"),
    # depthwise_kernel resolves per-layer: SeparableConv stores it as
    # 'dW', DepthwiseConvolution2D as its main 'W' — see
    # _resolve_depthwise below
    "pointwise_kernel": ("pW", None),
}


def _resolve_depthwise(layer_params, arr):
    """(key, reshaped array) for a Keras depthwise_kernel, or (None, arr).

    Keras lays the kernel out (kh, kw, C, M); ours is grouped-conv HWIO
    (kh, kw, 1, C*M) — a row-major reshape of the last two dims maps
    channel c / multiplier m to output feature c*M + m exactly."""
    key = "dW" if "dW" in layer_params else (
        "W" if "W" in layer_params else None)
    if key is None:
        return None, arr
    target = tuple(layer_params[key].shape)
    if tuple(arr.shape) == target:
        return key, arr
    if arr.ndim == 4 and target[2] == 1 \
            and arr.shape[:2] == target[:2] \
            and arr.shape[2] * arr.shape[3] == target[3]:
        return key, arr.reshape(target)
    return None, arr


def _remap_lstm_gates(arr):
    """Keras gate order i,f,g,o → ours i,f,o,g (kernel, recurrent kernel AND
    bias all share the 4*n gate axis — the reference remaps all three)."""
    n = arr.shape[-1] // 4
    i, f, g, o = (arr[..., :n], arr[..., n:2 * n],
                  arr[..., 2 * n:3 * n], arr[..., 3 * n:])
    return np.concatenate([i, f, o, g], axis=-1)


def _is_deconv(layer):
    """Transposed convs store Keras kernels as (..., OUT, IN) — the only
    kernel layout that differs from ours (HWIO); everything else imports
    natively."""
    if layer is None:
        return False
    from deeplearning4j_tpu.nn.conf.layers import Deconvolution2D
    from deeplearning4j_tpu.nn.conf.layers3d import Deconvolution3D
    return isinstance(layer, (Deconvolution2D, Deconvolution3D))


def _assign_keras_weights(layer_params, arrs, layer_state=None,
                          deconv=False):
    """Assign Keras .h5 arrays onto our param/state dicts BY NAME.

    Shape-only matching mis-assigns any layer whose weights share a shape
    (BatchNorm's four (C,) vectors; LSTM with nIn == nOut) — matching by
    the Keras dataset name is how the reference's KerasLayer does it.
    Arrays with unrecognized names fall back to shape matching against
    still-unused keys.
    """
    # LSTM only: U is (n_out, 4*n_out); SimpleRNN's U is square — its
    # weights must NOT be gate-remapped even when units % 4 == 0
    u = layer_params.get("U")
    is_lstm = u is not None and u.shape[-1] == 4 * u.shape[0]
    used_p, used_s = set(), set()
    leftovers = []
    for name, arr in arrs:
        if name == "depthwise_kernel":
            pkey, arr = _resolve_depthwise(layer_params, arr)
            skey = None
        else:
            pkey, skey = _KERAS_WEIGHT_NAMES.get(name, (None, None))
        if deconv and name == "kernel" and arr.ndim >= 3:
            # Keras Conv*Transpose computes the GRADIENT-style transposed
            # conv; our lax.conv_transpose(transpose_kernel=False) call
            # needs the channel axes swapped ((..., out, in) → HWIO) AND
            # every spatial axis flipped for identical outputs (verified
            # against a hand oracle in test_keras_import). Must be
            # unconditional for deconvs — a square in==out kernel would
            # otherwise pass the shape check untransposed.
            arr = arr.swapaxes(-1, -2)
            arr = arr[tuple(slice(None, None, -1)
                            for _ in range(arr.ndim - 2))]
        if pkey is not None and pkey in layer_params \
                and tuple(layer_params[pkey].shape) == tuple(arr.shape):
            if is_lstm and pkey in ("W", "U", "b") and arr.shape[-1] % 4 == 0:
                arr = _remap_lstm_gates(arr)
            layer_params[pkey] = arr
            used_p.add(pkey)
        elif skey is not None and layer_state is not None \
                and skey in layer_state \
                and tuple(layer_state[skey].shape) == tuple(arr.shape):
            layer_state[skey] = arr
            used_s.add(skey)
        else:
            leftovers.append(arr)
    for arr in leftovers:  # unknown names: shape-match unused keys only
        placed = False
        for key, val in layer_params.items():
            if key not in used_p and tuple(val.shape) == tuple(arr.shape):
                layer_params[key] = arr
                used_p.add(key)
                placed = True
                break
        if not placed and layer_state is not None:
            for key, val in layer_state.items():
                if key not in used_s and tuple(val.shape) == tuple(arr.shape):
                    layer_state[key] = arr
                    used_s.add(key)
                    break


def _np_tree(d):
    import jax
    return jax.tree_util.tree_map(np.array, d)


def _jnp_tree(d):
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(jnp.asarray, d)


def _assign_layer_weights(params, arrs, state, layer=None):
    """Assign one Keras layer group onto our (possibly NESTED) param dict.
    Bidirectional wrappers nest {'fwd': ..., 'bwd': ...}; their Keras
    datasets carry forward/ / backward/ prefixes from _h5_layer_weights."""
    deconv = _is_deconv(layer)
    if any(isinstance(v, dict) for v in params.values()):
        fwd = [(n.split("/", 1)[1], a) for n, a in arrs
               if n.startswith("forward/")]
        bwd = [(n.split("/", 1)[1], a) for n, a in arrs
               if n.startswith("backward/")]
        if isinstance(params.get("fwd"), dict) and fwd:
            _assign_keras_weights(params["fwd"], fwd, None)
        if isinstance(params.get("bwd"), dict) and bwd:
            _assign_keras_weights(params["bwd"], bwd, None)
        flat = [(n, a) for n, a in arrs if "/" not in n]
        flat_params = {k: v for k, v in params.items()
                       if not isinstance(v, dict)}
        if flat and flat_params:
            _assign_keras_weights(flat_params, flat, state)
            params.update(flat_params)
        return
    # plain layers never carry direction prefixes; strip any stray ones
    arrs = [(n.split("/", 1)[-1], a) for n, a in arrs]
    _assign_keras_weights(params, arrs, state, deconv=deconv)


def _load_h5_weights_multilayer(net, weights_path):
    by_name = _h5_layer_weights(weights_path)
    loaded = 0
    for li, lyr in enumerate(net.conf.layers):
        name = getattr(lyr, "name", None)
        if name in by_name and str(li) in net._params:
            params = _np_tree(net._params[str(li)])
            state = {k: np.array(v)
                     for k, v in net._state.get(str(li), {}).items()}
            _assign_layer_weights(params, by_name[name], state, layer=lyr)
            net._params[str(li)] = _jnp_tree(params)
            if state:
                net._state[str(li)] = _jnp_tree(state)
            loaded += 1
    net._h5_layers_loaded = loaded  # callers needing strictness check this
    return net


def _load_h5_weights_graph(net, weights_path):
    by_name = _h5_layer_weights(weights_path)
    loaded = 0
    for name, arrs in by_name.items():
        if name in net._params:
            params = _np_tree(net._params[name])
            state = {k: np.array(v)
                     for k, v in net._state.get(name, {}).items()}
            _assign_layer_weights(params, arrs, state,
                                  layer=getattr(net.nodes.get(name), "ref",
                                                None)
                                  if hasattr(net, "nodes") else None)
            net._params[name] = _jnp_tree(params)
            if state:
                net._state[name] = _jnp_tree(state)
            loaded += 1
    net._h5_layers_loaded = loaded
    return net
