"""Graph structure + random walks (≡ deeplearning4j-graph ::
org.deeplearning4j.graph.graph.Graph, api.IGraph, api.Edge/Vertex,
iterator.RandomWalkIterator / WeightedRandomWalkIterator,
data.EdgeLineProcessor-style loading).

Host-side adjacency structure (graph topology is pointer-shaped and
stays on the CPU, as the reference's does); what goes to the TPU is the
fixed-shape walk-id tensors DeepWalk trains on (see
``deeplearning4j_tpu.graph.deepwalk``).
"""
from __future__ import annotations

import numpy as np

__all__ = ["Vertex", "Edge", "Graph", "RandomWalkIterator",
           "WeightedRandomWalkIterator"]


class Vertex:
    """≡ api.Vertex — index + arbitrary value."""

    def __init__(self, idx, value=None):
        self.idx = int(idx)
        self.value = value

    def vertexID(self):
        return self.idx

    def getValue(self):
        return self.value


class Edge:
    """≡ api.Edge — (from, to, value, directed)."""

    def __init__(self, from_idx, to_idx, value=None, directed=False):
        self.from_idx = int(from_idx)
        self.to_idx = int(to_idx)
        self.value = value
        self.directed = bool(directed)

    def getFrom(self):
        return self.from_idx

    def getTo(self):
        return self.to_idx


class Graph:
    """≡ graph.Graph(numVertices, allowMultipleEdges)."""

    def __init__(self, num_vertices, allow_multiple_edges=False,
                 vertices=None):
        self._n = int(num_vertices)
        self._allow_multi = bool(allow_multiple_edges)
        self._vertices = (vertices if vertices is not None
                          else [Vertex(i) for i in range(self._n)])
        self._adj = [[] for _ in range(self._n)]      # per-vertex [(to, w)]
        self._edges = []

    # -- mutation --------------------------------------------------------
    def addEdge(self, from_idx, to_idx, value=1.0, directed=False):
        f, t = int(from_idx), int(to_idx)
        if not (0 <= f < self._n and 0 <= t < self._n):
            raise ValueError(f"edge ({f},{t}) out of range [0,{self._n})")
        w = 1.0 if value is None else float(value)
        if not self._allow_multi:
            fwd = any(d == t for d, _ in self._adj[f])
            rev = f != t and any(d == f for d, _ in self._adj[t])
            if directed:
                if fwd:
                    return
            elif fwd or rev:
                # an undirected request over an existing directed edge
                # upgrades it: add only the missing reverse direction so
                # adjacency never holds a duplicate (t, w) entry
                if fwd and (rev or f == t):
                    return
                self._edges.append(Edge(f, t, w, directed))
                if not fwd:
                    self._adj[f].append((t, w))
                if not rev and f != t:
                    self._adj[t].append((f, w))
                return
        self._edges.append(Edge(f, t, w, directed))
        self._adj[f].append((t, w))
        if not directed and f != t:
            self._adj[t].append((f, w))

    # -- queries (IGraph surface) ---------------------------------------
    def numVertices(self):
        return self._n

    def numEdges(self):
        return len(self._edges)

    def getVertex(self, idx):
        return self._vertices[idx]

    def getVertexDegree(self, idx):
        return len(self._adj[idx])

    def getConnectedVertexIndices(self, idx):
        return np.array([t for t, _ in self._adj[idx]], np.int32)

    def getEdgesOut(self, idx):
        return list(self._adj[idx])

    @staticmethod
    def loadEdgeList(path, num_vertices, directed=False, delimiter=None,
                     weighted=False):
        """≡ data.GraphLoader.loadUndirectedGraphEdgeListFile: one
        "from to [weight]" line per edge; '#' comments skipped."""
        g = Graph(num_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                w = float(parts[2]) if weighted and len(parts) > 2 else 1.0
                g.addEdge(int(parts[0]), int(parts[1]), w, directed)
        return g


class RandomWalkIterator:
    """≡ iterator.RandomWalkIterator: uniform random walks of fixed
    length from each vertex in turn. ``next()`` returns an int32 array
    of vertex ids (walkLength + 1 entries; walks from isolated vertices
    stay in place, as the reference's NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)."""

    def __init__(self, graph, walk_length, seed=123):
        self.graph = graph
        self.walk_length = int(walk_length)
        self._rng = np.random.RandomState(seed)
        self._order = None
        self._pos = 0
        self.reset()

    def reset(self):
        self._order = self._rng.permutation(self.graph.numVertices())
        self._pos = 0

    def hasNext(self):
        return self._pos < len(self._order)

    def _step(self, v):
        nbrs = self.graph._adj[v]
        if not nbrs:
            return v
        return nbrs[self._rng.randint(len(nbrs))][0]

    def next(self):
        v = int(self._order[self._pos])
        self._pos += 1
        walk = [v]
        for _ in range(self.walk_length):
            v = self._step(v)
            walk.append(v)
        return np.array(walk, np.int32)


class WeightedRandomWalkIterator(RandomWalkIterator):
    """≡ iterator.WeightedRandomWalkIterator: transition probability
    proportional to edge weight."""

    def _step(self, v):
        nbrs = self.graph._adj[v]
        if not nbrs:
            return v
        ws = np.array([w for _, w in nbrs], np.float64)
        p = ws / ws.sum()
        return nbrs[self._rng.choice(len(nbrs), p=p)][0]
