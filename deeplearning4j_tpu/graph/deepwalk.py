"""DeepWalk vertex embeddings (≡ deeplearning4j-graph ::
org.deeplearning4j.graph.models.deepwalk.DeepWalk + GraphVectors).

Reference shape: random walks over the graph feed a skip-gram model;
the reference trains it with hierarchical softmax over a Huffman tree
built from vertex-visit frequencies (``GraphHuffman``), updating one
pair at a time on the JVM.

TPU-first inversion: walks are generated host-side into fixed-shape
(center, context) int32 batches and trained with the SAME jitted
skip-gram negative-sampling executable the Word2Vec module uses
(``nlp.word2vec._sgns_step`` — embedding gathers + log-sigmoid loss +
SGD in one donated XLA program). Negative sampling replaces
hierarchical softmax: it is the batched-hardware-native formulation of
the same objective (the reference itself moved to it in sequencevectors),
and degree^0.75 negatives mirror the unigram^0.75 table.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.graph import RandomWalkIterator
from deeplearning4j_tpu.nlp.word2vec import _sgns_step

__all__ = ["DeepWalk", "GraphVectors", "GraphVectorsSerializer"]


class GraphVectors:
    """Lookup surface (≡ models.embeddings.GraphVectors)."""

    def getVertexVector(self, idx):
        return np.asarray(self.params["syn0"], np.float32)[int(idx)]

    def numVertices(self):
        return int(np.asarray(self.params["syn0"]).shape[0])

    def getVectorSize(self):
        return int(np.asarray(self.params["syn0"]).shape[1])

    def similarity(self, v1, v2):
        a, b = self.getVertexVector(v1), self.getVertexVector(v2)
        na, nb = np.linalg.norm(a), np.linalg.norm(b)
        if na == 0 or nb == 0:
            return 0.0
        return float(a @ b / (na * nb))

    def verticesNearest(self, idx, top=5):
        tab = np.asarray(self.params["syn0"], np.float32)
        v = tab[int(idx)]
        sims = tab @ v / np.maximum(
            np.linalg.norm(tab, axis=1) * max(np.linalg.norm(v), 1e-12),
            1e-12)
        order = [i for i in np.argsort(-sims) if i != int(idx)]
        return np.array(order[:top], np.int32)


class DeepWalk(GraphVectors):
    class Builder:
        def __init__(self):
            self._window = 4
            self._vector_size = 100
            self._lr = 0.025
            self._seed = 123
            self._negative = 5
            self._batch = 1024
            self._epochs = 1

        def windowSize(self, v):
            self._window = int(v); return self

        def vectorSize(self, v):
            self._vector_size = int(v); return self

        def learningRate(self, v):
            self._lr = float(v); return self

        def seed(self, v):
            self._seed = int(v); return self

        def negativeSample(self, v):
            self._negative = int(v); return self

        def batchSize(self, v):
            self._batch = int(v); return self

        def epochs(self, v):
            self._epochs = int(v); return self

        def build(self):
            return DeepWalk(self)

    def __init__(self, b):
        self.b = b
        self.params = None
        self._neg_table = None

    def initialize(self, graph):
        """≡ DeepWalk.initialize(IGraph): allocate tables."""
        n = graph.numVertices()
        rng = np.random.RandomState(self.b._seed)
        d = self.b._vector_size
        self.params = {
            "syn0": jnp.asarray((rng.rand(n, d).astype(np.float32) - 0.5) / d),
            "syn1": jnp.asarray(np.zeros((n, d), np.float32)),
        }
        deg = np.array([max(graph.getVertexDegree(i), 1) for i in range(n)],
                       np.float64) ** 0.75
        self._neg_table = (deg / deg.sum()).astype(np.float64)

    def fit(self, graph_or_iter, walk_length=None):
        """≡ fit(IGraph, walkLength) or fit(GraphWalkIterator)."""
        if walk_length is not None:
            it = RandomWalkIterator(graph_or_iter, walk_length,
                                    seed=self.b._seed)
            graph = graph_or_iter
        else:
            it = graph_or_iter
            graph = it.graph
        if self.params is None:
            self.initialize(graph)
        rng = np.random.RandomState(self.b._seed + 1)
        for _ in range(self.b._epochs):
            it.reset()
            centers, contexts = [], []
            while it.hasNext():
                walk = it.next()
                for i, c in enumerate(walk):
                    lo = max(0, i - self.b._window)
                    hi = min(len(walk), i + self.b._window + 1)
                    for j in range(lo, hi):
                        if j != i:
                            centers.append(c)
                            contexts.append(walk[j])
            centers = np.array(centers, np.int32)
            contexts = np.array(contexts, np.int32)
            order = rng.permutation(len(centers))
            centers, contexts = centers[order], contexts[order]
            bsz, k = self.b._batch, self.b._negative
            n_vocab = len(self._neg_table)
            for s in range(0, len(centers), bsz):
                c = centers[s:s + bsz]
                t = contexts[s:s + bsz]
                m = len(c)
                if m < bsz:  # pad to the jitted batch shape, mask the tail
                    c = np.pad(c, (0, bsz - m))
                    t = np.pad(t, (0, bsz - m))
                negs = rng.choice(n_vocab, size=(bsz, k),
                                  p=self._neg_table).astype(np.int32)
                w = np.zeros(bsz, np.float32)
                w[:m] = 1.0
                self.params, _ = _sgns_step(
                    self.params, jnp.float32(self.b._lr),
                    jnp.asarray(c), jnp.asarray(t), jnp.asarray(negs),
                    jnp.asarray(w))
        return self


class GraphVectorsSerializer:
    """≡ deeplearning4j-graph :: models.embeddings.GraphVectorsSerializer.
    Vertex embeddings in word2vec C format with vertex ids as the words —
    interoperable with WordVectorSerializer/loadStaticModel tooling."""

    @staticmethod
    def writeGraphVectors(deepwalk, path, binary=False):
        from deeplearning4j_tpu.nlp.serializer import (StaticWordVectors,
                                                       WordVectorSerializer)
        table = np.asarray(deepwalk.params["syn0"], np.float32)
        shim = StaticWordVectors(table,
                                 [str(i) for i in range(table.shape[0])])
        WordVectorSerializer.writeWord2VecModel(shim, path, binary=binary)

    @staticmethod
    def readGraphVectors(path, binary=None):
        """Returns a GraphVectors with vertex i at table row i. The file
        must use contiguous integer vertex ids as its words."""
        from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
        sv = WordVectorSerializer.readWord2VecModel(path, binary=binary)
        table = np.asarray(sv._table(), np.float32)
        order = []
        for i in range(table.shape[0]):
            idx = sv.vocab.indexOf(str(i))
            if idx < 0:
                raise ValueError(
                    f"not a graph-vectors file: vertex id {i} missing "
                    f"(words must be the contiguous ids 0..{table.shape[0] - 1})")
            order.append(idx)
        gv = GraphVectors()
        gv.params = {"syn0": table[order]}
        return gv
