"""Graph embeddings (≡ deeplearning4j-graph)."""
from deeplearning4j_tpu.graph.deepwalk import (DeepWalk, GraphVectors,
                                               GraphVectorsSerializer)
from deeplearning4j_tpu.graph.graph import (Edge, Graph, RandomWalkIterator,
                                            Vertex,
                                            WeightedRandomWalkIterator)

__all__ = ["Graph", "Vertex", "Edge", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "DeepWalk", "GraphVectors",
           "GraphVectorsSerializer"]
