"""Host-side paged-KV bookkeeping: page allocator, per-slot page tables,
and hash-of-prefix sharing with copy-on-write.

The device side (decode.py / kernels/flash_attention.py) stores KV in a
pooled layout `(L, P, H, ps, Dh)` — P physical pages of `ps` rows each —
and every read/write goes through a per-slot page index, so a ragged
request pays `ceil(len/ps)` pages instead of a whole cache rung
(µ-cuDNN's fixed-block decomposition applied to cache memory). THIS
module is the other half: pure-numpy/python allocation decisions made on
the host BETWEEN dispatches. Page-table updates ride the existing
dispatch/fetch boundaries — zero device syncs, zero traces (the
generation fast-path lints walk these functions).

Layout contract (mirrored by `BertDecoder` paged mode):

- physical page 0 is the NULL page: unmapped table entries point at it,
  and redundant writes (shared-prefix re-prefill, the frozen-lane
  rewrite past a request's budget) are redirected into it. Its contents
  are garbage by design and never covered by a validity mask.
- pages 1..P-1 are allocatable.

Prefix sharing: at admission each FULL page of the prompt is keyed by
`sha1(tokens[0 : (j+1)·ps])` — causal attention makes a page's KV rows a
pure function of the tokens up to its end — plus the prompt bucket (the
prefill executable that produced the bytes), so a hit maps the slot's
page-table entry at an existing read-only physical page and skips the
redundant write. The partial TAIL page (rows `m·ps..plen-1`) is keyed by
the whole prompt and shared only between identical prompts; it is the
one shared page a slot ever writes into (generation starts at `plen`),
so `ensure_range` copy-on-writes it to a fresh private page before the
first diverging dispatch. Released shared pages stay resident COLD
(refs == 0) so the next identical system prompt still hits; cold pages
are the eviction currency — freed LRU on allocation pressure and by the
memory-pressure ladder's evict-cold-pages rung.
"""
from __future__ import annotations

import hashlib

import numpy as np

from deeplearning4j_tpu.resilience.errors import PagePoolExhaustedError

__all__ = ["PageAllocator", "NULL_PAGE"]

#: physical id of the write-discard / unmapped-read page
NULL_PAGE = 0


def _digest(tokens):
    """Order-exact digest of a token prefix (any int sequence)."""
    h = hashlib.sha1()
    for t in tokens:
        h.update(b"%d," % int(t))
    return h.digest()


class _Shared:
    """One shared (read-only) physical page: its dedup key, how many
    live slots reference it, and an LRU tick for cold eviction."""
    __slots__ = ("phys", "refs", "tick")

    def __init__(self, phys, tick):
        self.phys = phys
        self.refs = 1
        self.tick = tick


class _Entry:
    """One per-slot page-table entry: the physical page and, when the
    page is shared, its registry key (None ⇒ private, writable)."""
    __slots__ = ("phys", "key")

    def __init__(self, phys, key=None):
        self.phys = phys
        self.key = key


class PageAllocator:
    """Free-list allocator over `pages` physical pages of `page_size`
    rows (page 0 reserved as the null page), with a prefix-sharing
    registry. Not thread-safe by design: every caller runs on the
    decode loop thread; `stats`/`occupancy()` reads from other threads
    see monotonic ints (same contract as the server's stats dict)."""

    def __init__(self, pages, page_size):
        if pages < 2:
            raise ValueError(
                f"page pool needs >= 2 pages (null + 1), got {pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(pages)
        self.page_size = int(page_size)
        self.stats = {"prefix_hits": 0, "pages_reused": 0,
                      "cow_copies": 0, "evictions": 0}
        self.reset()

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        """Forget everything (pool contents presumed lost) — the
        crash-recovery path: replay re-admissions rebuild the table and
        re-register prefixes deterministically from the journal."""
        self._free = list(range(self.num_pages - 1, NULL_PAGE, -1))
        self._slots = {}          # slot -> [_Entry]
        self._shared = {}         # key -> _Shared
        self._fresh = {}          # slot -> keys registered by its admit
        self._tick = 0

    # -- allocation core ---------------------------------------------------
    def _alloc(self):
        if not self._free:
            if not self.evict_cold(1):
                raise PagePoolExhaustedError(
                    f"no free KV pages ({self.num_pages - 1} total, "
                    f"0 cold evictable)")
        return self._free.pop()

    def evict_cold(self, want=None):
        """Free up to `want` cold shared pages (refs == 0), oldest
        first; `want=None` evicts ALL cold pages (the ladder's
        evict-cold-pages rung). Returns the number evicted."""
        cold = sorted((s.tick, k) for k, s in self._shared.items()
                      if s.refs == 0)
        if want is not None:
            cold = cold[:want]
        for _, key in cold:
            self._free.append(self._shared.pop(key).phys)
        self.stats["evictions"] += len(cold)
        return len(cold)

    # -- admission ---------------------------------------------------------
    def admit_slot(self, slot, prompt, pbucket):
        """Map `slot`'s prompt onto pages; returns the write-redirect
        row for the prefill dispatch: `wrow[j]` is the physical page
        prefill writes logical page j into — NULL_PAGE for pages whose
        bytes already exist (shared hit) or that hold only bucket
        padding. Raises `PagePoolExhaustedError` (allocations rolled
        back) when the pool cannot cover the non-shared pages."""
        ps = self.page_size
        plen = len(prompt)
        npp = -(-int(pbucket) // ps)          # prefill pages (ceil)
        need = -(-plen // ps)                 # pages holding real rows
        self.release_slot(slot)
        self._tick += 1
        entries, wrow, hits, fresh = [], np.zeros(npp, np.int32), 0, []
        try:
            for j in range(need):
                if (j + 1) * ps <= plen:      # full page
                    key = (b"p", j, _digest(prompt[:(j + 1) * ps]),
                           int(pbucket))
                else:                         # partial tail page
                    key = (b"t", plen, _digest(prompt[:plen]),
                           int(pbucket))
                shared = self._shared.get(key)
                if shared is not None:
                    shared.refs += 1
                    shared.tick = self._tick
                    entries.append(_Entry(shared.phys, key))
                    hits += 1                 # write already on device
                else:
                    phys = self._alloc()
                    self._shared[key] = _Shared(phys, self._tick)
                    entries.append(_Entry(phys, key))
                    fresh.append(key)
                    wrow[j] = phys
        except PagePoolExhaustedError:
            self._slots[slot] = entries
            self._fresh[slot] = fresh
            self.abort_admit(slot)
            raise
        self._slots[slot] = entries
        self._fresh[slot] = fresh
        if hits:
            self.stats["prefix_hits"] += 1
            self.stats["pages_reused"] += hits
        return wrow

    def abort_admit(self, slot):
        """Roll back a failed admission BEFORE its prefill dispatch
        executed: keys this admission registered point at never-written
        pages, so they are unregistered outright (a plain
        `release_slot` would leave them resident cold and serve garbage
        to the next identical prompt)."""
        for key in self._fresh.pop(slot, ()):
            shared = self._shared.pop(key, None)
            if shared is not None:
                self._free.append(shared.phys)
        for e in self._slots.pop(slot, ()):
            if e.key is None:
                self._free.append(e.phys)
            elif e.key in self._shared:
                self._deref(e.key)

    # -- steady state ------------------------------------------------------
    def ensure_range(self, slot, lo, hi):
        """Guarantee `slot` can WRITE rows `lo..hi`: allocate private
        pages through `hi // ps` and copy-on-write any shared page in
        the write window. Returns the list of `(src, dst)` physical
        page copies the caller must dispatch BEFORE the block."""
        ps = self.page_size
        entries = self._slots.setdefault(slot, [])
        cow = []
        for j in range(lo // ps, hi // ps + 1):
            while j >= len(entries):
                entries.append(_Entry(self._alloc()))
            e = entries[j]
            if e.key is not None:             # shared → private copy
                dst = self._alloc()
                cow.append((e.phys, dst))
                self._deref(e.key)
                entries[j] = _Entry(dst)
        self.stats["cow_copies"] += len(cow)
        return cow

    def _deref(self, key):
        shared = self._shared.get(key)
        if shared is not None:
            shared.refs -= 1
            shared.tick = self._tick

    def release_slot(self, slot):
        """Return `slot`'s private pages to the free list; shared pages
        just drop a reference (content stays resident for future
        prefix hits until evicted cold)."""
        self._tick += 1
        self._fresh.pop(slot, None)
        for e in self._slots.pop(slot, ()):  # noqa: B020
            if e.key is None:
                self._free.append(e.phys)
            else:
                self._deref(e.key)

    def build_table(self, slots, maxp):
        """Materialize the `(S, maxp)` int32 page table for one
        dispatch at the current rung width (`maxp = rung // ps`);
        unmapped entries read the null page (hidden by the cache
        mask)."""
        tab = np.zeros((slots, maxp), np.int32)
        for slot, entries in self._slots.items():
            for j, e in enumerate(entries):
                if j >= maxp:
                    break
                tab[slot, j] = e.phys
        return tab

    # -- observability -----------------------------------------------------
    def occupancy(self):
        """Pool occupancy snapshot for /generation and /health: how
        many allocatable pages exist, are mapped by live slots, sit
        cold-but-resident, or are free."""
        mapped = sum(len(v) for v in self._slots.values())
        shared_live = sum(1 for s in self._shared.values() if s.refs > 0)
        cold = sum(1 for s in self._shared.values() if s.refs == 0)
        total = self.num_pages - 1
        return {"pages_total": total,
                "pages_active": total - len(self._free) - cold,
                "pages_mapped": mapped,
                "pages_shared": shared_live,
                "pages_cold": cold,
                "pages_free": len(self._free),
                "page_size": self.page_size}
