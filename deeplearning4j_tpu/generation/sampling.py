"""Batched token sampling for the decode loop — pure, jit-friendly.

Every knob is a TRACED per-slot value (method id, temperature, top-k),
not a static python argument: the whole continuous batch samples in one
fused op inside the decode-step executable, and a newly admitted
request can carry different sampling settings than its in-flight
neighbours WITHOUT a recompile — the (bucket, cache-rung) executable
set stays closed over sampling configuration.

RNG is an explicit per-slot key column `(S, 2) uint32`: each sampling
step splits every slot's key and consumes the subkey, so a slot's token
stream is a pure function of its admission key — reproducible per
request, independent of which other requests share the batch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["GREEDY", "SAMPLE", "method_id", "sample_step", "split_keys"]

#: per-slot sampling method ids (device i32)
GREEDY = 0
SAMPLE = 1     # temperature (+ optional top-k) categorical

_NEG = -1e30


def method_id(name):
    """'greedy' → GREEDY; 'sample'/'temperature'/'top_k' → SAMPLE."""
    name = str(name).lower()
    if name == "greedy":
        return GREEDY
    if name in ("sample", "temperature", "top_k", "topk"):
        return SAMPLE
    raise ValueError(f"unknown sampling method {name!r}; expected "
                     "'greedy', 'temperature', or 'top_k'")


def split_keys(keys):
    """(S, 2) uint32 → (new_keys, subkeys), both (S, 2). One split per
    decode step keeps every slot's stream independent of its batch
    neighbours."""
    s = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return s[:, 0], s[:, 1]


def sample_step(logits, keys, method, temperature, top_k):
    """One batched sampling step.

    - logits: (S, V) float32
    - keys: (S, 2) uint32 per-slot rng keys
    - method: (S,) int32 — GREEDY or SAMPLE per slot
    - temperature: (S,) float32 (<= 0 treated as 1.0)
    - top_k: (S,) int32 — 0 (or >= V) disables the top-k filter

    Returns (tokens (S,) int32, new_keys (S, 2)). Greedy slots ignore
    their key (the split still advances, keeping streams aligned)."""
    v = logits.shape[-1]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / t
    # top-k threshold: kth-largest value per row (ascending sort, index
    # V-k); ties at the threshold stay in — a superset of k never
    # excludes the true top-k
    k_eff = jnp.clip(top_k, 0, v)
    srt = jnp.sort(scaled, axis=-1)
    kth = jnp.take_along_axis(
        srt, jnp.maximum(v - k_eff, 0)[:, None], axis=-1)
    use_k = ((k_eff > 0) & (k_eff < v))[:, None]
    filtered = jnp.where(use_k & (scaled < kth), _NEG, scaled)
    new_keys, subkeys = split_keys(keys)
    sampled = jax.vmap(jax.random.categorical)(subkeys, filtered)
    tokens = jnp.where(method == GREEDY, greedy_tok,
                       sampled.astype(jnp.int32))
    return tokens, new_keys
