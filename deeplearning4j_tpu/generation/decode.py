"""Decode-mode forwards: incremental single-token model evaluation over
donated device state.

Two adapters expose one contract to the GenerationServer:

- **BertDecoder** — transformer stacks built on `models/bert.py` params:
  per-layer K/V caches `(L, S, H, C, Dh)` (C = cache-length rung) with a
  rolling per-slot position index. `step` embeds the current token at
  its slot position, writes its K/V row, and attends the single query
  against the cached keys via `flash_attention_decode` (Pallas kernel on
  TPU, einsum elsewhere) — O(C) work per token instead of the O(T²)
  full-sequence re-forward. `prefill` runs the causal full forward over
  a length-bucketed prompt and writes the whole K/V block into the
  slot's cache rows in one shot.

- **RecurrentDecoder** — LSTM/GRU-style `MultiLayerNetwork`s
  (TextGenerationLSTM and friends): the decode state is the per-layer
  recurrent carry (h, c) rows, threaded through the network's own
  `_forward(carries=...)` path, so decode-step numerics are
  BIT-IDENTICAL to the full-sequence scan (tier-1 asserted).

The contract (all pure functions, traced into AOT executables by the
server — nothing here may touch the host):

    model_args()                  -> tuple of non-donated leading args
    step(margs, cache, tokens, pos)            -> (logits (S,V), cache')
    prefill(margs, cache, slot, prompt, plen)  -> (cache', logits (V,))
    grow(cache, new_len)          -> cache padded to a longer rung
    init_cache(slots, cache_len)  -> donated cache pytree

PAGED mode (`BertDecoder(..., page_size=ps, pool_pages=P)`): the cache
pytree becomes a pooled layout `(L, P, H, ps, Dh)` — P fixed-size pages
shared by every slot — and `step`/`verify`/`prefill` take the per-slot
page index the host allocator (generation/paging.py) computes between
dispatches (`ptab` (S, rung//ps) for decode reads/writes, `wrow`
(ceil(P_bucket/ps),) write-redirect for prefill). Physical page 0 is the
null page: unmapped reads land there (hidden by the cache mask) and
redundant writes (shared-prefix re-prefill, frozen-lane rewrites past a
request's budget) are redirected into it. `grow` is the identity — the
pool is rung-independent; a rung only sets the gathered view width — and
`page_copy` is the copy-on-write primitive. Attention reads through
`flash_attention_decode_paged` / `_mq_paged`, whose gather feeds the
UNCHANGED masked-softmax arithmetic, so paged streams are bit-identical
to slot-contiguous ones.
"""
from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.kernels.flash_attention import (
    flash_attention, flash_attention_decode, flash_attention_decode_mq,
    flash_attention_decode_mq_paged, flash_attention_decode_paged)
from deeplearning4j_tpu.models.bert import (_ffn, _layer_norm,
                                            bert_mlm_logits)
from deeplearning4j_tpu.parallel.ring_attention import dense_attention

__all__ = ["BertDecoder", "RecurrentDecoder"]


def _shape_tree_repr(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return repr((str(treedef),
                 tuple((tuple(l.shape), str(jnp.result_type(l)))
                       for l in leaves)))


class BertDecoder:
    """KV-cache decode over a `models/bert.py` parameter tree.

    The full-sequence reference this must match (≤ 1e-5) is
    `bert_encode(..., causal=True)` + `bert_mlm_logits` over the same
    prompt+generated prefix."""

    uses_cache_rungs = True
    n_model_args = 1

    def __init__(self, cfg, params, attn_impl="auto", kv_dtype="fp",
                 page_size=None, pool_pages=None):
        if cfg.moe_layers:
            raise ValueError(
                "BertDecoder does not support MoE layers (dense-dispatch "
                "expert FFNs have no single-token decode path yet)")
        if attn_impl not in ("auto", "dense", "pallas"):
            raise ValueError(
                f"attn_impl must be 'auto', 'dense' or 'pallas', "
                f"got {attn_impl!r}")
        if kv_dtype not in ("fp", "int8"):
            raise ValueError(
                f"kv_dtype must be 'fp' or 'int8', got {kv_dtype!r}")
        if kv_dtype == "int8" and attn_impl == "pallas":
            raise ValueError(
                "attn_impl='pallas' has no int8-cache variant — the "
                "quantized decode contraction runs the scale-folding "
                "einsum path; use attn_impl='auto' or 'dense' with "
                "kv_dtype='int8'")
        self.cfg = cfg
        self.params = params
        self.attn_impl = attn_impl
        # "int8": K/V rows stored int8 with per-(head, position) f32
        # scales (quantize/kvcache.py) and dequantized INSIDE
        # flash_attention_decode — the steady-state cache read (the
        # decode step's dominant traffic) drops to ~¼ width
        self.kv_dtype = kv_dtype
        self.vocab_size = int(cfg.vocab_size)
        self.max_cache_len = int(cfg.max_position_embeddings)
        # paged KV: pool_pages fixed-size pages of page_size rows each,
        # shared by all slots through a per-slot page index (page 0 is
        # the null page — see generation/paging.py for the layout
        # contract). pool_pages is the explicit HBM knob: a ragged
        # request costs ceil(len/ps) pages instead of a whole rung.
        self.paged = page_size is not None
        if self.paged:
            self.page_size = int(page_size)
            if self.page_size < 1:
                raise ValueError(
                    f"page_size must be >= 1, got {page_size}")
            if pool_pages is None:
                raise ValueError(
                    "paged mode needs an explicit pool_pages — the page "
                    "pool (not the rung) is the real HBM budget; "
                    "slots * rung // page_size + 1 reproduces the "
                    "slot-contiguous footprint")
            self.pool_pages = int(pool_pages)
            if self.pool_pages < 2:
                raise ValueError(
                    f"pool_pages must be >= 2 (null page + 1), "
                    f"got {pool_pages}")
        else:
            if pool_pages is not None:
                raise ValueError("pool_pages requires page_size")
            self.page_size = self.pool_pages = None

    def fingerprint(self):
        parts = ("bert-decode", repr(self.cfg), self.attn_impl,
                 self.kv_dtype, self.page_size, self.pool_pages,
                 _shape_tree_repr(self.params))
        return hashlib.sha256(repr(parts).encode()).hexdigest()[:16]

    def model_args(self):
        return (self.params,)

    def init_cache(self, slots, cache_len):
        cfg = self.cfg
        if self.paged:
            # pooled pages, slot- and rung-independent: the rung only
            # sets the gathered view width (ptab columns); HBM is
            # pool_pages × page_size rows, int8 halving page bytes
            shape = (cfg.num_layers, self.pool_pages, cfg.num_heads,
                     self.page_size, cfg.head_dim)
        else:
            shape = (cfg.num_layers, slots, cfg.num_heads, cache_len,
                     cfg.head_dim)
        if self.kv_dtype == "int8":
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "ks": jnp.ones(shape[:4], jnp.float32),
                    "vs": jnp.ones(shape[:4], jnp.float32)}
        dt = cfg.compute_dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def grow(self, cache, new_len):
        if self.paged:      # the pool is rung-independent
            return cache
        pad = [(0, 0)] * 5
        pad[3] = (0, int(new_len) - cache["k"].shape[3])
        out = {"k": jnp.pad(cache["k"], pad),
               "v": jnp.pad(cache["v"], pad)}
        if "ks" in cache:   # scale rows pad at 1 (zero rows round-trip)
            out["ks"] = jnp.pad(cache["ks"], pad[:4], constant_values=1.0)
            out["vs"] = jnp.pad(cache["vs"], pad[:4], constant_values=1.0)
        return out

    def page_copy(self, cache, src, dst):
        """Copy physical page `src` over `dst` across every layer and
        pool leaf — the copy-on-write primitive: the host allocator
        dispatches this (pre-compiled, donated) before the first block
        that would write into a shared page."""
        out = {}
        for name, t in cache.items():
            zeros = (0,) * (t.ndim - 2)
            pg = lax.dynamic_slice(
                t, (0, src) + zeros, (t.shape[0], 1) + t.shape[2:])
            out[name] = lax.dynamic_update_slice(t, pg, (0, dst) + zeros)
        return out

    def _embed(self, params, tokens, pos):
        """Token + position embedding at per-slot positions (mirrors
        bert_encode's embedding block; token_type unused in LM mode)."""
        emb = params["embeddings"]
        x = jnp.take(emb["word"], tokens, axis=0) \
            + jnp.take(emb["position"], pos, axis=0)
        return _layer_norm(x.astype(self.cfg.compute_dtype),
                           emb["ln_scale"], emb["ln_bias"],
                           self.cfg.layer_norm_eps)

    def _decode_attn(self, q, kc, vc, cmask, ks=None, vs=None):
        impl = self.attn_impl
        if impl == "auto":
            # int8 cache: the quantized decode GEMV reads the cache at
            # int8 width through the scale-folding einsum on every
            # backend (no Pallas int8-cache kernel yet; explicit
            # 'pallas' + int8 is rejected at construction)
            impl = ("pallas" if jax.default_backend() == "tpu"
                    and ks is None else "dense")
        return flash_attention_decode(q, kc, vc, cmask, impl=impl,
                                      k_scale=ks, v_scale=vs)

    def _decode_attn_paged(self, q, kp, vp, ptab, cmask, ks=None,
                           vs=None):
        impl = self.attn_impl
        if impl == "auto":
            impl = ("pallas" if jax.default_backend() == "tpu"
                    and ks is None else "dense")
        return flash_attention_decode_paged(q, kp, vp, ptab, cmask,
                                            impl=impl, k_scale_pool=ks,
                                            v_scale_pool=vs)

    def _prefill_attn(self, q, k, v):
        if self.attn_impl == "pallas" or (
                self.attn_impl == "auto"
                and jax.default_backend() == "tpu"):
            return flash_attention(q, k, v, causal=True)
        return dense_attention(q, k, v, causal=True)

    def step(self, margs, cache, tokens, pos, ptab=None):
        """One decode step for the whole batch: embed `tokens` at their
        slot positions, write each slot's K/V row at `pos`, attend the
        single query over rows 0..pos, and return next-token logits.
        `pos[s]` = number of already-cached tokens in slot s (the
        position the current token occupies). Paged mode additionally
        takes `ptab` (S, maxp) int32 — reads gather through it, the
        row write lands in page `pos // ps` at offset `pos % ps`, and
        frozen-lane writes past the mapped view (pos == C) are
        redirected to the null page (a dense cache silently DROPS that
        out-of-range scatter; pages must redirect it explicitly or the
        clamped index would corrupt a live row)."""
        (params,) = margs
        cfg = self.cfg
        x = self._embed(params, tokens, pos)            # (S, H)
        kc, vc = cache["k"], cache["v"]
        int8_kv = self.kv_dtype == "int8"
        ks = cache.get("ks")
        vs = cache.get("vs")
        s = tokens.shape[0]
        ar = jnp.arange(s)
        nh, hd = cfg.num_heads, cfg.head_dim
        paged = self.paged
        if paged:
            psz = self.page_size
            maxp = ptab.shape[1]
            c = maxp * psz
            poff = pos % psz
            phys = ptab[ar, jnp.minimum(pos // psz, maxp - 1)]
            wphys = jnp.where(pos < c, phys, 0)         # (S,)
        else:
            c = kc.shape[3]
        # rows 0..pos are valid (the current write included)
        cmask = jnp.arange(c)[None, :] <= pos[:, None]  # (S, C)
        dt = x.dtype
        for li, layer in enumerate(params["layers"]):
            qkv = x @ layer["qkv_W"].astype(dt) \
                + layer["qkv_b"].astype(dt)             # (S, 3H)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(s, nh, hd)
            k = k.reshape(s, nh, hd)
            v = v.reshape(s, nh, hd)
            if int8_kv:
                from deeplearning4j_tpu.quantize.kvcache import \
                    quantize_rows
                k, k_sc = quantize_rows(k)
                v, v_sc = quantize_rows(v)
                if paged:
                    ks = ks.at[li, wphys, :, poff].set(k_sc)
                    vs = vs.at[li, wphys, :, poff].set(v_sc)
                else:
                    ks = ks.at[li, ar, :, pos].set(k_sc)
                    vs = vs.at[li, ar, :, pos].set(v_sc)
            if paged:
                kc = kc.at[li, wphys, :, poff].set(k.astype(kc.dtype))
                vc = vc.at[li, wphys, :, poff].set(v.astype(vc.dtype))
                ctx = self._decode_attn_paged(
                    q, kc[li], vc[li], ptab, cmask,
                    ks[li] if int8_kv else None,
                    vs[li] if int8_kv else None).astype(dt)
            else:
                kc = kc.at[li, ar, :, pos].set(k.astype(kc.dtype))
                vc = vc.at[li, ar, :, pos].set(v.astype(vc.dtype))
                ctx = self._decode_attn(
                    q, kc[li], vc[li], cmask,
                    ks[li] if int8_kv else None,
                    vs[li] if int8_kv else None).astype(dt)
            a = ctx.reshape(s, cfg.hidden_size) \
                @ layer["proj_W"].astype(dt) + layer["proj_b"].astype(dt)
            x = _layer_norm(x + a, layer["ln1_scale"], layer["ln1_bias"],
                            cfg.layer_norm_eps)
            f = _ffn(cfg, layer, x, False, None)
            x = _layer_norm(x + f, layer["ln2_scale"], layer["ln2_bias"],
                            cfg.layer_norm_eps)
        logits = bert_mlm_logits(cfg, params, x[:, None, :])[:, 0]
        out = {"k": kc, "v": vc}
        if int8_kv:
            out["ks"] = ks
            out["vs"] = vs
        return logits, out

    @property
    def supports_draft(self):
        """Greedy drafting needs the multi-token `verify` forward; the
        int8 KV codec has no multi-row quantized write path yet, so
        drafting is fp-cache only."""
        return self.kv_dtype == "fp"

    def verify(self, margs, cache, tokens, pos, draft, ptab=None):
        """Draft-block decode: for each slot, run the q-block
        ``[tokens[s], draft[s, 0], ..., draft[s, d-2]]`` at positions
        ``pos[s] .. pos[s]+d-1`` through the stack in ONE dispatch —
        write all d K/V rows, attend each query over cache rows
        ``0 .. pos[s]+j`` (the intra-block causal offset), and return
        logits at every query: ``logits[s, j]`` is the model's
        next-token distribution after consuming j draft tokens.
        Exactly equal (same arithmetic, same masks) to d sequential
        `step` calls — the greedy-drafting acceptance rule's oracle.
        Rows written past the accepted prefix hold draft garbage but
        sit beyond the slot's advanced position, so the decode cache
        mask hides them until they are overwritten (same convention as
        prefill's padded rows). fp cache only (`supports_draft`)."""
        (params,) = margs
        cfg = self.cfg
        s = tokens.shape[0]
        d = 1 + draft.shape[1]
        tok_block = jnp.concatenate([tokens[:, None], draft], axis=1)
        pos_block = pos[:, None] + jnp.arange(d)[None, :]   # (S, d)
        x = self._embed(params, tok_block, pos_block)       # (S, d, H)
        kc, vc = cache["k"], cache["v"]
        ar = jnp.arange(s)
        nh, hd = cfg.num_heads, cfg.head_dim
        paged = self.paged
        if paged:
            psz = self.page_size
            maxp = ptab.shape[1]
            c = maxp * psz
            poff = pos_block % psz                          # (S, d)
            phys = ptab[ar[:, None],
                        jnp.minimum(pos_block // psz, maxp - 1)]
            wphys = jnp.where(pos_block < c, phys, 0)       # (S, d)
        else:
            c = kc.shape[3]
        # query j sees rows 0..pos+j (its own write included)
        qmask = jnp.arange(c)[None, None, :] <= pos_block[:, :, None]
        dt = x.dtype
        for li, layer in enumerate(params["layers"]):
            qkv = x @ layer["qkv_W"].astype(dt) \
                + layer["qkv_b"].astype(dt)                 # (S, d, 3H)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(s, d, nh, hd).transpose(0, 2, 1, 3)
            k = k.reshape(s, d, nh, hd)                     # (S, d, H, Dh)
            v = v.reshape(s, d, nh, hd)
            # advanced-index write: rows pos..pos+d-1 of every slot
            # (the advanced (S, d) block leads, then the H and Dh dims)
            if paged:
                kc = kc.at[li, wphys, :, poff].set(k.astype(kc.dtype))
                vc = vc.at[li, wphys, :, poff].set(v.astype(vc.dtype))
                ctx = flash_attention_decode_mq_paged(
                    q, kc[li], vc[li], ptab, qmask).astype(dt)
            else:
                kc = kc.at[li, ar[:, None], :, pos_block].set(
                    k.astype(kc.dtype))
                vc = vc.at[li, ar[:, None], :, pos_block].set(
                    v.astype(vc.dtype))
                ctx = flash_attention_decode_mq(q, kc[li], vc[li],
                                                qmask).astype(dt)
            a = ctx.transpose(0, 2, 1, 3).reshape(s, d, cfg.hidden_size) \
                @ layer["proj_W"].astype(dt) + layer["proj_b"].astype(dt)
            x = _layer_norm(x + a, layer["ln1_scale"], layer["ln1_bias"],
                            cfg.layer_norm_eps)
            f = _ffn(cfg, layer, x, False, None)
            x = _layer_norm(x + f, layer["ln2_scale"], layer["ln2_bias"],
                            cfg.layer_norm_eps)
        logits = bert_mlm_logits(cfg, params, x)            # (S, d, V)
        return logits, {"k": kc, "v": vc}

    def _write_prompt_pages(self, pool, block, wrow, li):
        """Scatter a prefill K/V (or scale) block into pool pages:
        `pool` is the full (L, P, nh, ps, ...) pool, `block` the layer's
        (nh, P_bucket, ...) rows, `wrow[j]` the physical page logical
        page j writes into — 0 (the null page) for pages whose bytes
        already exist on device (shared-prefix hit) or that hold only
        bucket padding, so redundant writes are discarded without
        branching."""
        psz = self.page_size
        npp = wrow.shape[0]
        pad = [(0, 0)] * block.ndim
        pad[1] = (0, npp * psz - block.shape[1])
        # (nh, npp·ps, ...) -> per-page (1, 1, nh, ps, ...) updates
        pages = jnp.pad(block, pad).reshape(
            (block.shape[0], npp, psz) + block.shape[2:])
        for j in range(npp):
            upd = pages[:, j][None, None]
            pool = lax.dynamic_update_slice(
                pool, upd.astype(pool.dtype),
                (li, wrow[j]) + (0,) * (pool.ndim - 2))
        return pool

    def prefill(self, margs, cache, slot, prompt, plen, wrow=None):
        """Causal full forward over one length-bucketed prompt (1, P);
        writes the slot's K/V block for rows 0..P-1 in one shot and
        returns the logits at the last REAL position (plen - 1). Rows
        beyond plen hold padding garbage — masked out by the decode
        cache mask (pos starts at plen), so a bucketed prompt serves
        bit-the-same as an exact-length one. Paged mode writes through
        the `wrow` redirect instead of the slot's rows (see
        `_write_prompt_pages`); the forward itself is identical, so a
        shared-prefix admission still yields exact first-token
        logits."""
        (params,) = margs
        cfg = self.cfg
        p_len = prompt.shape[0]
        emb = params["embeddings"]
        x = jnp.take(emb["word"], prompt[None], axis=0) \
            + emb["position"][None, :p_len]
        x = _layer_norm(x.astype(cfg.compute_dtype), emb["ln_scale"],
                        emb["ln_bias"], cfg.layer_norm_eps)
        kc, vc = cache["k"], cache["v"]
        int8_kv = self.kv_dtype == "int8"
        paged = self.paged
        ks = cache.get("ks")
        vs = cache.get("vs")
        nh, hd = cfg.num_heads, cfg.head_dim
        dt = x.dtype
        for li, layer in enumerate(params["layers"]):
            qkv = x @ layer["qkv_W"].astype(dt) \
                + layer["qkv_b"].astype(dt)             # (1, P, 3H)
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):
                return t.reshape(1, p_len, nh, hd).transpose(0, 2, 1, 3)

            q, k, v = heads(q), heads(k), heads(v)      # (1, nh, P, hd)
            if int8_kv:
                from deeplearning4j_tpu.quantize.kvcache import \
                    quantize_rows
                kq, k_sc = quantize_rows(k)             # (1, nh, P)
                vq, v_sc = quantize_rows(v)
                if paged:
                    kc = self._write_prompt_pages(kc, kq[0], wrow, li)
                    vc = self._write_prompt_pages(vc, vq[0], wrow, li)
                    ks = self._write_prompt_pages(ks, k_sc[0], wrow, li)
                    vs = self._write_prompt_pages(vs, v_sc[0], wrow, li)
                else:
                    kc = lax.dynamic_update_slice(
                        kc, kq[None], (li, slot, 0, 0, 0))
                    vc = lax.dynamic_update_slice(
                        vc, vq[None], (li, slot, 0, 0, 0))
                    ks = lax.dynamic_update_slice(
                        ks, k_sc[None], (li, slot, 0, 0))
                    vs = lax.dynamic_update_slice(
                        vs, v_sc[None], (li, slot, 0, 0))
            elif paged:
                kc = self._write_prompt_pages(kc, k[0], wrow, li)
                vc = self._write_prompt_pages(vc, v[0], wrow, li)
            else:
                kc = lax.dynamic_update_slice(
                    kc, k[None].astype(kc.dtype), (li, slot, 0, 0, 0))
                vc = lax.dynamic_update_slice(
                    vc, v[None].astype(vc.dtype), (li, slot, 0, 0, 0))
            ctx = self._prefill_attn(q, k, v)
            a = ctx.transpose(0, 2, 1, 3).reshape(1, p_len,
                                                  cfg.hidden_size) \
                @ layer["proj_W"].astype(dt) + layer["proj_b"].astype(dt)
            x = _layer_norm(x + a, layer["ln1_scale"], layer["ln1_bias"],
                            cfg.layer_norm_eps)
            f = _ffn(cfg, layer, x, False, None)
            x = _layer_norm(x + f, layer["ln2_scale"], layer["ln2_bias"],
                            cfg.layer_norm_eps)
        h_last = jnp.take(x[0], plen - 1, axis=0)       # (H,)
        logits = bert_mlm_logits(cfg, params, h_last[None, None, :])[0, 0]
        out = {"k": kc, "v": vc}
        if int8_kv:
            out["ks"] = ks
            out["vs"] = vs
        return out, logits


class RecurrentDecoder:
    """Carry-state decode over a recurrent `MultiLayerNetwork` (stacked
    LSTM/GRU/SimpleRnn + an RnnOutputLayer-style dense head, e.g. the
    zoo's TextGenerationLSTM).

    Tokens enter as one-hot feature vectors (char-RNN convention:
    head nOut == input feature width == vocab). The decode state is the
    recurrent carries, threaded through the network's OWN
    `_forward(carries=...)` path — a decode step is literally a T=1
    scan, so carries and logits are bit-identical to the full-sequence
    forward."""

    uses_cache_rungs = False
    n_model_args = 2

    def __init__(self, net):
        self.net = net
        layers = net.layers
        head = layers[-1]
        if not hasattr(head, "pre_activation"):
            raise ValueError(
                f"RecurrentDecoder needs a dense (RnnOutputLayer-style) "
                f"head with pre_activation; got {type(head).__name__}")
        rec = [l for l in layers[:-1]
               if getattr(l, "is_recurrent", False)]
        if not rec:
            raise ValueError(
                "RecurrentDecoder needs at least one recurrent layer")
        for l in rec:
            if not hasattr(l, "scan_apply"):
                raise ValueError(
                    f"{type(l).__name__} cannot run step-by-step "
                    "(no carried-state protocol)")
        it = getattr(net.conf, "input_type", None)
        if it is None or not hasattr(it, "size"):
            raise ValueError(
                "net conf has no sized recurrent InputType")
        self.n_features = int(it.size)
        self.vocab_size = int(head.nOut)
        if self.vocab_size != self.n_features:
            raise ValueError(
                f"char-RNN generation feeds sampled tokens back as "
                f"one-hot inputs: head nOut ({self.vocab_size}) must "
                f"equal the input feature width ({self.n_features})")
        # carry state is O(1) in sequence length: cache rungs are
        # meaningless — the server collapses them to a single rung that
        # only bounds prompt_len + max_new_tokens
        self.max_cache_len = None

    def fingerprint(self):
        from deeplearning4j_tpu.runtime.executables import \
            model_fingerprint
        return hashlib.sha256(
            ("recurrent-decode-" + model_fingerprint(self.net)).encode()
        ).hexdigest()[:16]

    def model_args(self):
        return (self.net._params, self.net._state)

    def init_cache(self, slots, cache_len):
        carries = {}
        for i, layer in enumerate(self.net.layers):
            if getattr(layer, "is_recurrent", False):
                carries[str(i)] = layer.zero_carry(int(slots))
        return {"carries": carries}

    def grow(self, cache, new_len):
        return cache    # carry state is length-independent

    def step(self, margs, cache, tokens, pos):
        """One decode step: one-hot the current tokens, run a T=1 pass
        through the network's carried-state forward, return the head's
        pre-activation logits (softmax-free: sampling works on logits)
        and the advanced carries.

        The step runs under an all-ones validity mask so it compiles
        into the SAME masked-scan graph family as the bucketed prefill
        and the canonical masked full-sequence forward — XLA fuses the
        gate math identically across that family (tested), which is
        what makes decode carries/logits BIT-identical to the
        full-sequence recompute rather than merely close."""
        params, state = margs
        s = tokens.shape[0]
        x = jax.nn.one_hot(tokens, self.n_features,
                           dtype=jnp.float32)[:, None, :]    # (S, 1, F)
        _, preact, _, _, carries = self.net._forward(
            params, state, x, False, None,
            mask=jnp.ones((s, 1), jnp.float32),
            carries=cache["carries"])
        return preact[:, 0].astype(jnp.float32), {"carries": carries}

    def prefill(self, margs, cache, slot, prompt, plen):
        """Run the length-bucketed prompt through the full scan under a
        validity mask (masked steps HOLD the carry — the recurrent
        layers' own masking contract), then graft the resulting carry
        rows into the slot. Returns the logits at the last real step."""
        params, state = margs
        p_len = prompt.shape[0]
        x = jax.nn.one_hot(prompt, self.n_features,
                           dtype=jnp.float32)[None]          # (1, P, F)
        mask = (jnp.arange(p_len)[None, :] < plen).astype(jnp.float32)
        _, preact, _, _, fresh = self.net._forward(
            params, state, x, False, None, mask=mask, carries={})
        carries = {}
        for idx, rows in cache["carries"].items():
            carries[idx] = tuple(
                lax.dynamic_update_slice(
                    full, one.astype(full.dtype),
                    (slot,) + (0,) * (full.ndim - 1))
                for full, one in zip(rows, fresh[idx]))
        logits = jnp.take(preact[0], plen - 1, axis=0).astype(jnp.float32)
        return {"carries": carries}, logits
