"""GenerationServer — continuous-batching autoregressive serving over
the AOT executable stack.

The chat-style scenario: long-lived stateful requests share one
fixed-shape decode batch. A background decode thread runs ONE
pre-compiled executable per token for the WHOLE batch; new requests are
admitted into free slots of the in-flight batch between steps
(prefill + cache graft, one dispatch) and finished ones retire without
ever changing a shape — the executable set is closed over
(slot bucket, cache-length rung, prompt bucket) exactly like
`ParallelInference`'s bucket ladder is closed over batch shapes.

Steady-state contract (linted by scripts/check_fastpath.py and
regression-tested): past `warmup()`, the decode loop performs ZERO jit
traces and ZERO XLA compiles — superstep, admit, retire, and grow all
resolve from the in-memory executable tier — and the ONLY host sync is
the per-SUPERSTEP sampled-token-block fetch (`_fetch_tokens`); the
whole decode state (KV caches / recurrent carries, positions, active
mask, per-slot sampling knobs, rng keys) lives on device and is
DONATED through every dispatch, so steady state is one fixed-shape
dispatch per k tokens. The token block is a non-donated output whose
host copy starts asynchronously (`_start_fetch`) right after dispatch:
block n's journal append and stream delivery run while block n+1
computes, so the fetch overlaps compute instead of gating it.

Executables (per `FunctionStore`, two-tier: in-memory + on-disk
serialized — a restarted replica warms from disk):

- ``("superstep", C, k)`` — decode k tokens for all S slots at cache
  rung C as ONE `lax.scan` dispatch: each iteration embeds → writes the
  K/V row (or advances carries) → single-query attention → logits →
  fused per-slot sampling (greedy / temperature / top-k, all TRACED
  per-slot values: mixed sampling configs share one executable).
  Per-slot EOS/budget halt masks freeze finished slots mid-block
  (frozen iterations are computed-but-masked, emitted as -1, never
  delivered), so the block's semantics exactly equal k sequential
  steps while dispatches and host fetches per token drop by k.
  Admission / retirement / growth happen between supersteps, so EOS
  retirement may lag up to ~2k steps behind the terminal token (one
  block of halt lag + one block of async-fetch pipeline depth).
- ``("verify", C, d)`` — exact greedy drafting (optional, off by
  default): the host proposes up to d draft tokens (prompt-lookup
  n-gram over the request's own journal; during crash-replay, the
  journaled prefix itself), and one dispatch runs the q-block
  [current, draft...] through a multi-query decode attention
  (`flash_attention_decode_mq`), accepting exactly the prefix of
  drafts that match the model's own greedy argmax. Delivered streams
  are token-identical to vanilla greedy; non-greedy slots in the same
  batch advance exactly one sampled token per round (one rng split),
  keeping the sampled-stream bit-identity contract untouched.
- ``("admit", C, P)`` — prefill one prompt at prompt bucket P, graft
  its cache/carry rows into a slot, arm the slot's sampling config and
  rng key, sample the first token.
- ``("retire",)`` — clear a slot's position/active/token columns
  (cache rows need no clearing: the cache-validity mask hides them).
- ``("grow_to_<C'>", C)`` — pad the KV cache from rung C to C' when an
  admission needs more room than the current rung (never shrinks
  mid-flight; recurrent carry state is rung-independent).
- ``("advance_key_n",)`` — advance one rng key past n consumed
  sampling splits in a single dispatch (the crash-replay
  continuation-key derivation).
- ``("page_copy",)`` — copy one physical KV page pool→pool (paged
  servers only: the copy-on-write primitive behind prefix sharing).

Paged KV mode (decoder built with ``page_size``/``pool_pages``): the
cache is a fixed pool of physical pages instead of S contiguous rung
rows, and every executable additionally threads a host-built page
index — superstep/verify take the ``(S, rung // page_size)`` int32 page
table, admit takes the per-logical-page write-redirect row. The pool is
RUNG-INDEPENDENT, so ``grow`` degenerates to a host-side rung relabel
(no dispatch, no per-rung-pair executables); the rung only sets the
page-table width the dispatch reads through. Between dispatches the
host `PageAllocator` (generation/paging.py) maps prompt pages with
hash-of-prefix dedup (identical prefixes share read-only pages),
allocates write coverage for the next block, and copy-on-writes shared
pages before their first divergent write — each CoW is one pre-compiled
``("page_copy",)`` dispatch. Page bookkeeping is pure host numpy on the
existing dispatch boundaries: zero extra syncs, zero traces (linted).
Pool exhaustion raises the typed `PagePoolExhaustedError` — refused
pre-dispatch at admission (fails only that request), and mid-stream it
carries the RESOURCE_EXHAUSTED token so the OOM classifier routes it
through the degradation ladder, whose paged form gains an
evict-cold-pages level between shed-queued and shrink-rung.

Survivability (the serving twin of the PR 5/7 training guardian):

- **Crash-replay.** Every admitted request carries a host-side journal
  (`_SlotJournal`: admission id → rng key derivation; the prompt,
  sampling config, and delivered tokens already live on the request —
  the per-step journal append IS the existing sampled-token fetch, so
  it costs nothing extra). A decode-loop failure no longer fails the
  in-flight batch: the state is rebuilt from the warm executable set
  and every surviving request is RE-ADMITTED — by re-prefilling
  prompt+generated-prefix with the admission key advanced past the
  consumed splits when the prefix fits a prompt bucket, else by
  re-generating the prefix from the original admission state with
  delivery suppressed. Either way the continuation stream is
  bit-identical to an uninterrupted run, because per-slot keys make
  every stream a pure function of its admission state (chaos-tested).
- **Supervised restart.** A failed recovery no longer latches the
  server dead: a supervisor retries the rebuild+replay from the warm
  `FunctionStore` (zero live compiles) under a bounded `RetryPolicy`;
  only an exhausted budget — or sustained zero forward progress —
  latches the typed `ServerDeadError`, which is pushed to every open
  stream immediately so no consumer waits out its timeout.
- **Memory-pressure degradation ladder.** An OOM-classified failure
  (or a `monitoring/memory.py` high-water reading) degrades stepwise
  instead of killing serving: (1) refuse further cache growth, (2)
  also shed queued admissions, (3) shrink to a smaller pre-compiled
  rung — in-flight requests replay into it, requests that no longer
  fit fail with the typed `MemoryPressureError`. Pressure decays after
  a clean stretch of steps. Events count `dl4j.gen.degradations`;
  replays and restarts count `dl4j.gen.{replays,restarts}`.

Admission rides the same bounded-enqueue/shed semantics as
`ParallelInference` (`InferenceOverloadedError`, enqueue timeout).
Chaos fault sites: `generation.step`, `generation.admit`, `cache.grow`,
and (paged servers) `cache.page` (resilience/faults.py) fire inside the
loop at zero disabled-path cost.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
import weakref

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.monitoring import requests as _req
from deeplearning4j_tpu.generation.paging import PageAllocator
from deeplearning4j_tpu.generation.sampling import (GREEDY, method_id,
                                                    sample_step,
                                                    split_keys)
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.errors import (InferenceOverloadedError,
                                                  InferenceTimeoutError,
                                                  MemoryPressureError,
                                                  PagePoolExhaustedError,
                                                  ReplayDivergedError,
                                                  ServerDeadError)
from deeplearning4j_tpu.resilience.policy import RetryPolicy
from deeplearning4j_tpu.util.crash_reporting import CrashReportingUtil

__all__ = ["GenerationRequest", "GenerationServer", "status"]

_SERVERS = weakref.WeakSet()

#: decode-state tuple layout (everything donated through each step)
_CACHE, _POS, _ACTIVE, _TOKENS, _RNG, _METHOD, _TEMP, _TOPK = range(8)


class GenerationRequest:
    """Handle for one submitted prompt: collects generated tokens,
    streams them (`stream()` / `on_token`), resolves via `result()`."""

    def __init__(self, prompt, max_new_tokens, eos_id, method,
                 temperature, top_k, on_token=None):
        self.prompt = prompt                  # np.int32 (plen,)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.method = method                  # sampling.GREEDY/SAMPLE
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.on_token = on_token
        self.tokens = []                      # generated token ids
        self.error = None
        self.finish_reason = None             # "eos" | "length" | "error"
        #: request-scoped tracing (monitoring/requests.py): None with
        #: monitoring off — every append below is one is-None branch
        self.trace = None
        self.trace_id = None
        self._done = threading.Event()
        self._stream = queue.Queue()

    # -- server side ------------------------------------------------------
    def _push(self, tok):
        self.tokens.append(tok)
        self._stream.put(tok)
        if self.on_token is not None:
            try:
                self.on_token(tok)
            except Exception:  # noqa: BLE001 — a bad callback must not
                pass           # kill the shared decode loop

    def _finish(self, reason):
        self.finish_reason = reason
        if self.trace is not None:
            self.trace.event("retire", reason=reason,
                             tokens=len(self.tokens))
            self.trace.finish(reason)
        self._done.set()
        self._stream.put(None)

    def _fail(self, exc):
        self.error = exc
        if self.trace is not None:
            self.trace.event("failed", error=type(exc).__name__)
        self._finish("error")

    # -- client side ------------------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the request finished; returns the generated
        token ids — when generation stopped on `eos_id`, the EOS token
        is the last element (finish_reason tells which case hit)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation request still in flight")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def stream(self, timeout=None):
        """Yield tokens as they are generated (ends at EOS/length).
        `timeout` bounds the wait per token (TimeoutError on expiry,
        matching result()). A server death pushes the terminal error
        sentinel immediately — consumers raise promptly, they never
        wait out the timeout on a dead decode loop."""
        while True:
            try:
                tok = self._stream.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    "generation stream produced no token within the "
                    "timeout") from None
            if tok is None:
                if self.error is not None:
                    raise self.error
                return
            yield tok


class _SlotJournal:
    """Host-side crash-replay journal for one admitted request.

    `admit_id` (the admission counter value) derives the slot's rng
    key; the prompt, sampling config, and delivered tokens live on the
    request itself — together they make the token stream a pure
    function of this record, which is exactly what `_replay_one` needs
    to continue an interrupted request bit-identically. While a
    re-generation replay is in flight, `expect` holds the
    already-delivered prefix and `replay_idx` the suppression cursor.
    `disp_pos` (paged servers) is the host upper bound of KV rows whose
    writes have been dispatched — the page allocator covers
    `[disp_pos, disp_pos + k)` before each block, so live writes always
    land on mapped private pages without ever syncing device `pos`."""

    __slots__ = ("req", "admit_id", "expect", "replay_idx", "disp_pos")

    def __init__(self, req, admit_id):
        self.req = req
        self.admit_id = admit_id
        self.expect = None
        self.replay_idx = 0
        self.disp_pos = 0


def _queued_req(item):
    """The GenerationRequest behind one admission-queue entry: adopted
    records (`adopt()`) ride the queue as their `_SlotJournal`, local
    submits as the bare request — drain/shed must fail either form."""
    return item.req if isinstance(item, _SlotJournal) else item


class _Block:
    """One in-flight sampled-token block: the device (k, S) output of a
    superstep/verify dispatch, the slot→journal map snapshotted at
    dispatch time (delivery must never hand a stale token to a slot
    re-admitted since), and the timing anchors for the per-token and
    fetch-overlap metrics. `proposed` is the per-slot draft-proposal
    count (drafting rounds only)."""

    __slots__ = ("tokens", "recs", "k", "t0", "t_copy", "proposed")

    def __init__(self, tokens, recs, k, t0, t_copy, proposed=None):
        self.tokens = tokens
        self.recs = recs
        self.k = k
        self.t0 = t0
        self.t_copy = t_copy
        self.proposed = proposed


def _ngram_propose(history, nd, n=3):
    """Prompt-lookup drafting: propose the `nd` tokens that followed
    the most recent PREVIOUS occurrence of the history's trailing
    n-gram (falling back to shorter grams down to a unigram). One
    vectorized sliding-window comparison per gram length — this runs
    on the decode hot path once per greedy slot per drafting round, so
    no per-position python loop. Wrong proposals cost nothing but the
    masked lanes of one verify dispatch; only exact greedy matches are
    ever delivered."""
    h = np.array(history, np.int32)
    t = len(h)
    for g in range(min(n, t - 1), 0, -1):
        gram = h[t - g:]
        # all candidate windows end before the trailing gram starts
        wins = np.lib.stride_tricks.sliding_window_view(h[:t - 1], g)
        hits = np.flatnonzero((wins == gram).all(axis=1))
        if len(hits):
            j = int(hits[-1])       # rightmost = freshest context wins
            tail = h[j + g:j + g + nd]
            if len(tail):
                return tail
    return h[:0]


class GenerationServer:
    """Continuous-batching KV-cache decode server over one model.

    `decoder`: a `generation.decode` adapter (BertDecoder /
    RecurrentDecoder) or a recurrent `MultiLayerNetwork` (wrapped
    automatically). `slots` is the decode batch bucket; `cache_lengths`
    the cache rungs (prompt_len + max_new_tokens must fit the top
    rung); `prompt_buckets` the prefill length ladder.

    Survivability knobs: `restart_policy` bounds supervised restarts
    after a failed recovery (default 3 attempts, short backoff);
    `max_consecutive_failures` bounds crash-recover churn with zero
    forward progress; `pressure_relief_steps` clean decode steps — or
    `pressure_relief_secs` of wall-clock quiet, whichever first —
    decay one memory-pressure level; `memory_high_water` (fraction of
    device memory, None disables) proactively refuses cache growth
    from the `monitoring/memory.py` telemetry (reported 'degraded'
    while it lasts)."""

    def __init__(self, decoder, slots=4, cache_lengths=(128,),
                 prompt_buckets=None, method="greedy", temperature=1.0,
                 top_k=0, eos_id=None, max_new_tokens=64, seed=0,
                 queue_limit=256, enqueue_timeout_ms=100.0,
                 exec_cache_dir=None, restart_policy=None,
                 max_consecutive_failures=8, pressure_relief_steps=256,
                 pressure_relief_secs=60.0, memory_high_water=0.92,
                 superstep=1, draft=0):
        from deeplearning4j_tpu.generation.decode import RecurrentDecoder
        if not hasattr(decoder, "init_cache"):
            decoder = RecurrentDecoder(decoder)
        self.decoder = decoder
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        self.superstep = int(superstep)
        if self.superstep < 1:
            raise ValueError("superstep must be >= 1")
        self.draft = int(draft)
        if self.draft < 0:
            raise ValueError("draft must be >= 0")
        if self.draft and self.superstep > 1:
            raise ValueError(
                "draft and superstep > 1 are alternative decode fast "
                "paths — a drafting round already amortizes the "
                "dispatch over up to draft+1 tokens; pick one")
        if self.draft and not getattr(decoder, "supports_draft", False):
            raise ValueError(
                f"{type(decoder).__name__} has no draft-verify forward "
                "(greedy drafting needs the multi-query KV-cache "
                "`verify` path — BertDecoder with kv_dtype='fp')")
        rungs = tuple(sorted({int(c) for c in cache_lengths}))
        if not rungs or rungs[0] < 2:
            raise ValueError(f"cache_lengths must be >= 2: {cache_lengths}")
        if not decoder.uses_cache_rungs:
            # carry state is O(1) in sequence length: one rung, which
            # only bounds prompt_len + max_new_tokens
            rungs = (rungs[-1],)
        if decoder.max_cache_len is not None \
                and rungs[-1] > decoder.max_cache_len:
            raise ValueError(
                f"top cache rung {rungs[-1]} exceeds the model's "
                f"maximum decodable length {decoder.max_cache_len}")
        self.cache_lengths = rungs
        #: paged-KV mode: decoder stores KV in a physical page pool and
        #: every dispatch reads through a host-built page table
        self.paged = bool(getattr(decoder, "paged", False))
        if self.paged:
            ps = int(decoder.page_size)
            bad = [c for c in rungs if c % ps]
            if bad:
                raise ValueError(
                    f"paged decode needs cache rungs divisible by the "
                    f"page size {ps}: {bad}")
            self._pages = PageAllocator(decoder.pool_pages, ps)
        else:
            self._pages = None
        if prompt_buckets is None:
            prompt_buckets, b = [], 8
            while b < rungs[-1]:
                prompt_buckets.append(b)
                b *= 2
            prompt_buckets.append(rungs[-1])
        self.prompt_buckets = tuple(sorted({int(p)
                                            for p in prompt_buckets}))
        if self.prompt_buckets[-1] > rungs[-1]:
            raise ValueError("prompt buckets cannot exceed the top "
                             "cache rung")
        self.default_method = method_id(method)
        self.default_temperature = float(temperature)
        self.default_top_k = int(top_k)
        self.default_eos_id = eos_id
        self.default_max_new_tokens = int(max_new_tokens)
        self.seed = int(seed)
        self.enqueue_timeout = float(enqueue_timeout_ms) / 1e3
        # a caller-supplied policy sets the budget/backoff knobs but is
        # NEVER mutated (it may be shared with other servers/trainers):
        # the supervisor runs a private clone whose classifier is the
        # server's own _restartable — restart classification (retry
        # transients AND shrinkable OOMs, refuse a dead latch) is the
        # server's call, not the policy's
        rp = restart_policy or RetryPolicy(
            max_attempts=3, initial_backoff=0.02, max_backoff=0.5)
        self.restart_policy = RetryPolicy(
            max_attempts=rp.max_attempts,
            initial_backoff=rp.initial_backoff,
            max_backoff=rp.max_backoff, multiplier=rp.multiplier,
            jitter=rp.jitter, deadline=rp.deadline, seed=self.seed,
            sleep=rp._sleep, clock=rp._clock,
            classifier=self._restartable)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.pressure_relief_steps = int(pressure_relief_steps)
        # wall-clock decay: a server whose remaining traffic is all
        # refused (or that idles) takes no decode steps, so step-count
        # relief alone would leave it degraded forever after one
        # transient OOM — elapsed quiet time relieves too
        self.pressure_relief_secs = (None if pressure_relief_secs is None
                                     else float(pressure_relief_secs))
        self.memory_high_water = (None if memory_high_water is None
                                  else float(memory_high_water))
        self.stats = {"tokens": 0, "steps": 0, "supersteps": 0,
                      "admissions": 0, "retirements": 0, "errors": 0,
                      "replays": 0, "restarts": 0, "degradations": 0,
                      "draft_accepts": 0, "draft_rejects": 0}
        self.token_fetches = 0       # host syncs: ONE per decode block
        self._queue = queue.Queue(maxsize=int(queue_limit))
        self._store = None           # FunctionStore, built at warmup
        self._exec_cache_dir = exec_cache_dir
        self._exes = {}              # (name, *) -> bare executable call
        self._margs = None           # non-donated model args
        self._state = None           # donated decode-state tuple
        self._rung = None
        self._slot_req = {}          # slot -> _SlotJournal
        self._inflight = None        # _Block dispatched, not delivered
        self._latencies = collections.deque(maxlen=512)  # per-token ms
        self._replaying = []         # journals awaiting re-admission
        self._free = list(range(self.slots))
        self._counter = 0            # admission counter (rng derivation)
        # RLock: recovery replays deliveries (user on_token callbacks)
        # under the lock; a callback calling submit() must not deadlock
        self._lock = threading.RLock()
        self._work = threading.Event()
        self._shutdown = False
        self._dead = None            # typed ServerDeadError once latched
        self._pressure = 0           # ladder level (0..3; paged 0..4)
        self._page_counts = {"prefix_hits": 0, "evictions": 0}
        self._rung_cap = None        # growth cap while under pressure
        self._clean_steps = 0        # steps since the last OOM event
        self._pressure_ts = 0.0      # monotonic time of last escalation
        self._consecutive_failures = 0   # incidents without a delivery
        self._warm = False
        self._thread = None
        self._corr = "genserver-%x" % id(self)   # ops-event incident key
        _SERVERS.add(self)

    # -- warmup (the declared trace/compile boundary) ---------------------
    def warmup(self):
        """Build the whole closed executable set — superstep (or
        draft-verify) per rung, retire, admit per (rung, prompt
        bucket), grow per rung pair, the replay key-advance — through
        the two-tier FunctionStore (warm
        replica: deserialize, no XLA compile), initialize the device
        decode state at the smallest rung, and start the decode loop.
        Idempotent (and safe under concurrent first submits)."""
        with self._lock:
            return self._warmup_locked()

    def _warmup_locked(self):
        if self._warm:
            return {"compiled": 0, "from_disk": 0, "seconds": 0.0,
                    "executables": len(self._exes)}
        from deeplearning4j_tpu.runtime.executables import FunctionStore
        t0 = time.perf_counter()
        # slots is part of every executable's SHAPE but not of the
        # (name, rung, bucket) keys — it must be part of the store
        # identity or two servers over the same model with different
        # slot counts would share (wrong-shaped) disk entries
        store = FunctionStore(
            f"{self.decoder.fingerprint()}-s{self.slots}",
            directory=self._exec_cache_dir)
        if self.draft:
            store.register("verify", self._traced_verify(self.draft),
                           donate_argnums=self._donate_range())
        else:
            store.register("superstep",
                           self._traced_superstep(self.superstep),
                           donate_argnums=self._donate_range())
        store.register("admit", self._traced_admit,
                       donate_argnums=self._donate_range())
        store.register("retire", self._traced_retire,
                       donate_argnums=(0, 1, 2))
        if self.paged:
            store.register(
                "page_copy",
                lambda cache, src, dst: self.decoder.page_copy(
                    cache, src, dst),
                donate_argnums=(0,))
        store.register(
            "advance_key_n",
            lambda k, n: lax.fori_loop(
                0, n, lambda _, kk: split_keys(kk[None])[0][0], k))
        self._margs = tuple(self.decoder.model_args())
        sds = jax.ShapeDtypeStruct
        scalar_i = sds((), jnp.int32)
        scalar_f = sds((), jnp.float32)
        slot_i = sds((self.slots,), jnp.int32)
        for ci, rung in enumerate(self.cache_lengths):
            spec = self._state_spec(rung)
            margs_spec = jax.tree_util.tree_map(
                lambda l: sds(jnp.shape(l), jnp.result_type(l)),
                self._margs)
            # paged mode threads the page table through every decode
            # dispatch; its width is the rung's page count
            ptab = ((sds((self.slots,
                          rung // self.decoder.page_size), jnp.int32),)
                    if self.paged else ())
            if self.draft:
                key = ("verify", rung, self.draft)
                e = store.load_or_compile(
                    key, (*margs_spec, *spec, slot_i, slot_i,
                          sds((self.slots, self.draft), jnp.int32),
                          slot_i, *ptab))
            else:
                key = ("superstep", rung, self.superstep)
                e = store.load_or_compile(
                    key, (*margs_spec, *spec, slot_i, slot_i, *ptab))
            self._exes[key] = e.call
            for p in self.prompt_buckets:
                if p > rung:
                    continue
                wrow = ((sds((-(-p // self.decoder.page_size),),
                             jnp.int32),) if self.paged else ())
                key = ("admit", rung, p)
                e = store.load_or_compile(
                    key, (*margs_spec, *spec, scalar_i,
                          sds((p,), jnp.int32), scalar_i,
                          sds((2,), jnp.uint32), scalar_i, scalar_f,
                          scalar_i, *wrow))
                self._exes[key] = e.call
            if self.paged:
                # the pool is rung-independent: growth is a host-side
                # rung relabel, no grow executables exist
                continue
            for bigger in self.cache_lengths[ci + 1:]:
                name = f"grow_to_{bigger}"
                store.register(
                    name,
                    lambda cache, _to=bigger: self.decoder.grow(cache,
                                                                _to),
                    donate_argnums=(0,))
                key = (name, rung)
                e = store.load_or_compile(key, (spec[_CACHE],))
                self._exes[key] = e.call
        key = ("retire",)
        e = store.load_or_compile(
            key, (sds((self.slots,), jnp.int32),
                  sds((self.slots,), jnp.bool_),
                  sds((self.slots,), jnp.int32), scalar_i))
        self._exes[key] = e.call
        key = ("advance_key_n",)
        e = store.load_or_compile(key, (sds((2,), jnp.uint32),
                                        scalar_i))
        self._exes[key] = e.call
        if self.paged:
            key = ("page_copy",)
            e = store.load_or_compile(
                key, (self._state_spec(self.cache_lengths[0])[_CACHE],
                      scalar_i, scalar_i))
            self._exes[key] = e.call
        self._store = store
        self._rung = self.cache_lengths[0]
        self._state = self._init_state(self._rung)
        self._warm = True
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()
        return {"compiled": store.stats["compiles"],
                "from_disk": store.stats["disk_hits"],
                "seconds": time.perf_counter() - t0,
                "executables": len(self._exes)}

    def _donate_range(self):
        n = len(tuple(self.decoder.model_args()))
        return tuple(range(n, n + 8))

    def _state_spec(self, rung):
        sds = jax.ShapeDtypeStruct
        s = self.slots
        cache = jax.eval_shape(
            lambda: self.decoder.init_cache(s, rung))
        return (cache, sds((s,), jnp.int32), sds((s,), jnp.bool_),
                sds((s,), jnp.int32), sds((s, 2), jnp.uint32),
                sds((s,), jnp.int32), sds((s,), jnp.float32),
                sds((s,), jnp.int32))

    def _init_state(self, rung):
        s = self.slots
        return (self.decoder.init_cache(s, rung),
                jnp.zeros((s,), jnp.int32),
                jnp.zeros((s,), jnp.bool_),
                jnp.zeros((s,), jnp.int32),
                jnp.zeros((s, 2), jnp.uint32),
                jnp.zeros((s,), jnp.int32),
                jnp.ones((s,), jnp.float32),
                jnp.zeros((s,), jnp.int32))

    # -- traced bodies (pure; lowered once per signature at warmup) -------
    def _traced_superstep(self, k):
        """k decode steps as ONE lax.scan dispatch. Per-slot halt masks
        freeze a slot the moment it samples its EOS token or exhausts
        its budget — frozen iterations keep recomputing the held token
        at the held position (idempotent cache writes, masked -1
        output), so the block's semantics exactly equal k sequential
        steps with host-side retirement; retirement itself happens
        after delivery, up to k steps late. `eos` is -1 for slots with
        no EOS (sampled ids are always >= 0, so it never matches);
        `budget` is the per-slot count of tokens the block may still
        emit (see _superstep_args for the replay accounting)."""

        def superstep(*args):
            n = self.decoder.n_model_args
            margs = args[:n]
            if self.paged:
                (cache, pos, active, tokens, rng, method, temp, topk,
                 eos, budget, ptab) = args[n:]
            else:
                (cache, pos, active, tokens, rng, method, temp, topk,
                 eos, budget) = args[n:]
                ptab = None

            def body(carry, _):
                cache, pos, active, tokens, rng, budget = carry
                if ptab is None:
                    logits, cache = self.decoder.step(margs, cache,
                                                      tokens, pos)
                else:
                    logits, cache = self.decoder.step(margs, cache,
                                                      tokens, pos, ptab)
                sampled, rng = sample_step(logits, rng, method, temp,
                                           topk)
                out = jnp.where(active, sampled, -1)
                budget = budget - active.astype(jnp.int32)
                halt = (sampled == eos) | (budget <= 0)
                tokens = jnp.where(active, sampled, tokens)
                pos = jnp.where(active, pos + 1, pos)
                active = active & ~halt
                return (cache, pos, active, tokens, rng, budget), out

            (cache, pos, active, tokens, rng, _), outs = lax.scan(
                body, (cache, pos, active, tokens, rng, budget), None,
                length=k)
            return (cache, pos, active, tokens, rng, method, temp,
                    topk, outs)                           # outs (k, S)

        return superstep

    def _traced_verify(self, ndraft):
        """One greedy-drafting round as ONE dispatch: the decoder's
        multi-query `verify` forward scores the q-block
        [current, draft...], and the acceptance rule delivers the
        longest prefix of draft tokens matching the model's own greedy
        argmax, plus the model's next token — so every delivered token
        IS the vanilla greedy token (exactness by construction), and a
        full match delivers ndraft+1 tokens for one dispatch. Non-
        greedy slots ride the same dispatch with a zero-length draft
        (host-enforced): they deliver exactly one sampled token per
        round with exactly one rng split — their streams stay
        bit-identical to the undrafted path. EOS/budget truncate the
        delivered prefix and freeze the slot like the superstep."""
        d = ndraft + 1

        def verify(*args):
            n = self.decoder.n_model_args
            margs = args[:n]
            if self.paged:
                (cache, pos, active, tokens, rng, method, temp, topk,
                 eos, budget, draft, dlen, ptab) = args[n:]
                logits, cache = self.decoder.verify(
                    margs, cache, tokens, pos, draft, ptab)  # (S, d, V)
            else:
                (cache, pos, active, tokens, rng, method, temp, topk,
                 eos, budget, draft, dlen) = args[n:]
                logits, cache = self.decoder.verify(
                    margs, cache, tokens, pos, draft)        # (S, d, V)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # position 0 samples with the slot's own config (ONE split
            # per round — greedy slots ignore the key, sampled slots
            # deliver exactly this one token)
            first, rng = sample_step(logits[:, 0], rng, method, temp,
                                     topk)
            cand = jnp.concatenate([first[:, None], greedy[:, 1:]],
                                   axis=1)                 # (S, d)
            # draft j consumed iff every draft token <= j matched the
            # model's prediction (prefix rule)
            ok = ((jnp.arange(ndraft)[None, :] < dlen[:, None])
                  & (cand[:, :ndraft] == draft))
            m = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
            js = jnp.arange(d)[None, :]
            deliver = (js <= m[:, None]) & (js < budget[:, None])
            # stop AFTER the first delivered EOS (it is itself emitted)
            is_eos = deliver & (cand == eos[:, None])
            before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
                - is_eos.astype(jnp.int32)
            deliver &= (before == 0) & active[:, None]
            out = jnp.where(deliver, cand, -1)             # (S, d)
            ndel = deliver.sum(axis=1).astype(jnp.int32)
            pos = pos + ndel
            budget = budget - ndel
            last = jnp.take_along_axis(
                cand, jnp.clip(ndel - 1, 0, d - 1)[:, None],
                axis=1)[:, 0]
            tokens = jnp.where(ndel > 0, last, tokens)
            active = active & ~(is_eos.any(axis=1) | (budget <= 0))
            return (cache, pos, active, tokens, rng, method, temp,
                    topk, out.T)                           # (d, S)

        return verify

    def _traced_admit(self, *args):
        n = self.decoder.n_model_args
        margs = args[:n]
        if self.paged:
            (cache, pos, active, tokens, rng, method, temp, topk,
             slot, prompt, plen, key, m, t, k, wrow) = args[n:]
            cache, logits = self.decoder.prefill(margs, cache, slot,
                                                 prompt, plen, wrow)
        else:
            (cache, pos, active, tokens, rng, method, temp, topk,
             slot, prompt, plen, key, m, t, k) = args[n:]
            cache, logits = self.decoder.prefill(margs, cache, slot,
                                                 prompt, plen)
        first, key2 = sample_step(logits[None], key[None], m[None],
                                  t[None], k[None])
        pos = pos.at[slot].set(plen)
        active = active.at[slot].set(True)
        tokens = tokens.at[slot].set(first[0])
        rng = rng.at[slot].set(key2[0])
        method = method.at[slot].set(m)
        temp = temp.at[slot].set(t)
        topk = topk.at[slot].set(k)
        return (cache, pos, active, tokens, rng, method, temp, topk,
                first[0])

    @staticmethod
    def _traced_retire(pos, active, tokens, slot):
        return (pos.at[slot].set(0),
                active.at[slot].set(False),
                tokens.at[slot].set(0))

    # -- client side ------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id="default",
               method=None, temperature=None, top_k=None, on_token=None,
               timeout_ms=None):
        """Queue one prompt for generation; returns a GenerationRequest
        immediately (tokens stream in as the decode loop reaches it).
        Admission is bounded: a full queue sheds with
        InferenceOverloadedError after the enqueue timeout; a dead
        server refuses with the latched ServerDeadError."""
        from deeplearning4j_tpu.parallel.inference import bounded_enqueue
        if not self._warm:
            self.warmup()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the top prompt "
                f"bucket {self.prompt_buckets[-1]}")
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new > self.cache_lengths[-1]:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds the top cache rung {self.cache_lengths[-1]}")
        req = GenerationRequest(
            prompt, max_new,
            self.default_eos_id if eos_id == "default" else eos_id,
            self.default_method if method is None else method_id(method),
            (self.default_temperature if temperature is None
             else temperature),
            self.default_top_k if top_k is None else top_k,
            on_token=on_token)
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1e3)
        req.trace = _req.start("generation", meta={
            "prompt_len": int(prompt.size),
            "max_new_tokens": req.max_new_tokens,
            "method": req.method})
        if req.trace is not None:
            req.trace_id = req.trace.trace_id
            req.trace.event("enqueue", queued=self._queue.qsize())
        # liveness check + enqueue are ONE locked step: a request must
        # never land in the queue after shutdown()/_die() drained it
        # (nothing would ever fail or serve it — result() would hang)
        try:
            with self._lock:
                if self._shutdown:
                    raise RuntimeError("GenerationServer is shut down")
                if self._dead is not None:
                    raise self._dead
                bounded_enqueue(self._queue, req, deadline,
                                self.enqueue_timeout, what="generation")
        except BaseException as e:
            if req.trace is not None:
                # classify the rejection so a ring full of dead-server
                # refusals never reads as load shedding: only the
                # bounded-queue overload is a "shed"
                if isinstance(e, InferenceOverloadedError):
                    status = "shed"
                elif isinstance(e, InferenceTimeoutError):
                    status = "timeout"
                else:
                    status = "rejected"
                req.trace.event(status, error=type(e).__name__)
                req.trace.finish(status)
            if _mon.enabled():
                _events.emit(
                    "generation", _events.SERVER_REFUSED,
                    attrs={"error": type(e).__name__,
                           "request": getattr(req, "trace_id", None)},
                    correlation_id=self._corr)
            raise
        self._work.set()
        return req

    def generate(self, prompt, timeout=None, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(prompt, **kw).result(timeout=timeout)

    def adopt(self, req, admit_id, timeout_ms=None):
        """Admit a pre-built request under an EXPLICIT admission id —
        the fleet-router hook behind cross-replica failover. A stream
        is a pure function of (server seed, admit_id, prompt, sampling
        config), so a router that keeps replica seeds aligned and
        assigns fleet-wide admission ids gets streams independent of
        WHICH replica serves them. `req.tokens` may already hold the
        delivered prefix of a request whose replica died mid-stream:
        the record then re-enters through the existing crash-replay
        machinery (prefix re-prefill, or re-generation with delivery
        suppressed), so the continuation is bit-identical to an
        uninterrupted run and nothing is ever re-delivered."""
        from deeplearning4j_tpu.parallel.inference import bounded_enqueue
        if not self._warm:
            self.warmup()
        plen = int(req.prompt.size)
        if plen < 1:
            raise ValueError("prompt must hold at least one token")
        if plen > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {plen} exceeds the top prompt "
                f"bucket {self.prompt_buckets[-1]}")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if plen + req.max_new_tokens > self.cache_lengths[-1]:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the top cache rung "
                f"{self.cache_lengths[-1]}")
        rec = _SlotJournal(req, int(admit_id))
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1e3)
        # same locked liveness check + bounded enqueue as submit(): the
        # record must never land in a queue shutdown()/_die() drained
        with self._lock:
            if self._shutdown:
                raise RuntimeError("GenerationServer is shut down")
            if self._dead is not None:
                raise self._dead
            bounded_enqueue(self._queue, rec, deadline,
                            self.enqueue_timeout, what="generation")
        self._work.set()
        return req

    # -- decode loop ------------------------------------------------------
    def _loop(self):
        while not self._shutdown:
            try:
                self._admit_pending()
                if self._slot_req:
                    self._dispatch_block()
                elif self._inflight is not None:
                    # every occupant retired, but the pipelined tail
                    # block is still in flight: drain it (its live
                    # slots were all frozen — rows of -1 — but the
                    # fetch/step accounting must balance)
                    blk, self._inflight = self._inflight, None
                    self._deliver_block(blk)
                else:
                    if self._pressure:
                        # an idle server takes no steps and may see no
                        # growth attempts: wall-clock relief must fire
                        # from here or /health stays degraded forever
                        self._maybe_relieve_by_time()
                    if not self._work.wait(timeout=0.05):
                        continue
                    self._work.clear()
            except Exception as e:  # noqa: BLE001 — replay, stay up
                if not self._survive(e):
                    return

    def _admit_pending(self):
        """Admit queued requests into free slots of the in-flight batch
        — one prefill dispatch each, no shape changes (a longer request
        may first GROW the cache to a pre-compiled bigger rung).

        Failure containment: a degradation-ladder refusal
        (`MemoryPressureError`) is raised BEFORE any dispatch, so it
        fails only the triggering request and admission continues. Any
        later failure happens after the request was journaled and after
        a donating dispatch may have poisoned `self._state` (real on
        TPU; CPU ignores donation) — it propagates so `_survive`
        rebuilds the state and REPLAYS every journaled request,
        including the one whose admission crashed. (Size/shape
        validation already happened at submit()/adopt().)"""
        while self._free:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            # adopted records (fleet failover / explicit-id admission)
            # ride the queue AS their journal; local submits are bare
            # requests that get their journal in _admit_one
            rec = item if isinstance(item, _SlotJournal) else None
            req = rec.req if rec is not None else item
            try:
                if rec is None:
                    self._admit_one(req)
                else:
                    self._admit_adopted(rec)
            except MemoryPressureError as e:
                req._fail(e)      # pre-dispatch refusal: state intact
                continue
            except Exception as e:  # noqa: BLE001 — see docstring
                if not any(r.req is req
                           for r in self._slot_req.values()):
                    # failed before the journal was registered: nothing
                    # will replay it — fail it so no caller hangs
                    req._fail(e)
                raise

    def _admit_one(self, req):
        """Fresh admission: assign the next admission id (the rng-key
        derivation the journal replays) and dispatch."""
        self._counter += 1
        self._admit_fresh(_SlotJournal(req, self._counter))

    def _admit_adopted(self, rec):
        """Admit a router-journaled record (`adopt()`): one with no
        delivered prefix admits exactly like a local submission, just
        under its explicit id; one carrying a delivered prefix is a
        mid-stream failover and re-enters through `_replay_one` — the
        same journal-replay path an in-process crash uses — so the
        continuation stays bit-identical and exactly-once. A record
        whose prefix already carries the terminal token only lost its
        retirement to the dead replica: finish it, never generate past
        EOS / max_new_tokens."""
        req = rec.req
        if req.done():
            return
        reason = self._finished_reason(req)
        if reason is not None:
            req._finish(reason)
            return
        if req.tokens:
            self._replay_one(rec)
        else:
            self._admit_fresh(rec)

    def _admit_fresh(self, rec):
        """Dispatch one journaled first-time admission and count it."""
        req = rec.req
        t0 = time.perf_counter()
        self._admit_rec(rec, req.prompt, self._admit_key(rec.admit_id))
        prefill_ms = (time.perf_counter() - t0) * 1e3
        self.stats["admissions"] += 1
        self.stats["tokens"] += 1     # the prefill's first sampled token
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GEN_ADMISSIONS,
                        help="sequences admitted into the decode "
                             "batch").inc()
            reg.counter(_mon.GEN_TOKENS,
                        help="tokens generated (all slots)").inc()
            reg.histogram(_mon.GEN_PREFILL_MS,
                          help="prompt prefill + cache-graft wall "
                               "time").observe(prefill_ms,
                                               trace_id=req.trace_id)
            reg.gauge(_mon.GEN_ACTIVE_SLOTS,
                      help="occupied decode slots").set(
                len(self._slot_req))

    def _admit_rec(self, rec, prompt, key):
        """Admission dispatch shared by fresh admissions and
        crash-replay re-admissions: gate growth through the degradation
        ladder, JOURNAL the record before the first donating dispatch
        (a post-donation crash re-admits it from the journal), grow if
        needed, prefill, and deliver the first sampled token (delivery
        is suppressed while the record replays an already-delivered
        prefix)."""
        req = rec.req
        plen = int(prompt.size)
        pbucket = next(p for p in self.prompt_buckets if p >= plen)
        needed = int(req.prompt.size) + req.max_new_tokens
        rung = self._rung
        if needed > rung or pbucket > rung:
            rung = self._rung_for(needed, pbucket)
            self._check_growth(rung)    # raises MemoryPressureError
        if self._pages is not None and _faults.ACTIVE is not None:
            # fired BEFORE the slot pop: an injected admission-time
            # pool fault (MemoryPressureError-classified) is contained
            # to the request without leaking the slot
            _faults.ACTIVE.fire(_faults.CACHE_PAGE)
        slot = self._free.pop()
        wrow = None
        if self._pages is not None:
            try:
                wrow = self._pages.admit_slot(slot, prompt, pbucket)
            except PagePoolExhaustedError:
                # PRE-dispatch refusal (allocations rolled back): the
                # slot goes back untouched and only this request fails
                self._free.append(slot)
                if _mon.enabled():
                    _events.emit(
                        "generation", _events.PAGES_EXHAUSTED,
                        attrs={"request": getattr(req, "trace_id", None)},
                        correlation_id=self._corr)
                raise
            rec.disp_pos = plen
        self._slot_req[slot] = rec
        if rung != self._rung:
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.fire(_faults.CACHE_GROW)
            if req.trace is not None:
                req.trace.event("grow", to_rung=rung)
            if _mon.enabled():
                _events.emit("generation", _events.CACHE_GROWN,
                             attrs={"to_rung": rung},
                             correlation_id=self._corr)
            if self._pages is not None:
                # the pool is rung-independent: growth just widens the
                # page table the next dispatches read through
                self._rung = rung
            else:
                call = self._exes[(f"grow_to_{rung}", self._rung)]
                cache = call(self._state[_CACHE])
                self._state = (cache,) + self._state[1:]
                self._rung = rung
        if req.trace is not None:
            req.trace.event("admit", slot=slot, rung=rung,
                            bucket=pbucket, admit_id=rec.admit_id)
        padded = np.zeros((pbucket,), np.int32)
        padded[:plen] = prompt
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.GENERATION_ADMIT)
        call = self._exes[("admit", rung, pbucket)]
        extra = () if wrow is None else (wrow,)
        out = call(*self._margs, *self._state, np.int32(slot), padded,
                   np.int32(plen), key, np.int32(req.method),
                   np.float32(req.temperature), np.int32(req.top_k),
                   *extra)
        self._state = tuple(out[:8])
        if self._pages is not None:
            self._emit_page_metrics()
        first = int(self._fetch_tokens(out[8]))
        self._deliver(slot, rec, first)

    def _admit_key(self, admit_id):
        """Per-request admission rng key: a pure function of
        (server seed, admission id) — the identity crash-replay re-derives."""
        return np.random.default_rng(
            (self.seed, admit_id)).integers(0, 2 ** 32, size=2,
                                            dtype=np.uint32)

    def _rung_for(self, needed, pbucket):
        """Smallest pre-compiled cache rung admitting a request that
        needs `needed` rows and prefills at prompt bucket `pbucket`."""
        return next(c for c in self.cache_lengths
                    if c >= needed and c >= pbucket)

    def _superstep_args(self):
        """Per-dispatch EOS/budget columns: pure functions of the host
        journal at dispatch time. A replay-suppressed slot's budget
        includes its undelivered journaled prefix (the device must
        regenerate it before the live continuation). With a block
        already in flight, its undelivered tokens are not yet counted,
        so the budget may over-allow by up to one block — delivery
        clamps exactly at max_new/EOS, so overshoot is
        computed-but-dropped, never delivered."""
        eos = np.full((self.slots,), -1, np.int32)
        budget = np.zeros((self.slots,), np.int32)
        for slot, rec in self._slot_req.items():
            req = rec.req
            if req.eos_id is not None:
                eos[slot] = req.eos_id
            left = req.max_new_tokens - len(req.tokens)
            if rec.expect is not None:
                left += len(rec.expect) - rec.replay_idx
            budget[slot] = max(left, 0)
        return eos, budget

    def _propose_drafts(self):
        """Host-side draft proposal (pure numpy over the request
        journal — no device work, no syncs): a replaying slot proposes
        its journaled prefix (a guaranteed-exact draft); a live GREEDY
        slot proposes the prompt-lookup n-gram continuation of its own
        history; non-greedy slots propose nothing (their sampled
        streams must consume exactly one rng split per token)."""
        nd = self.draft
        draft = np.zeros((self.slots, nd), np.int32)
        dlen = np.zeros((self.slots,), np.int32)
        for slot, rec in self._slot_req.items():
            req = rec.req
            if req.method != GREEDY:
                continue
            if rec.expect is not None:
                tail = rec.expect[rec.replay_idx:rec.replay_idx + nd]
            else:
                tail = _ngram_propose(
                    np.concatenate([req.prompt,
                                    np.array(req.tokens, np.int32)]),
                    nd)
            if len(tail):
                draft[slot, :len(tail)] = tail
                dlen[slot] = len(tail)
        return draft, dlen

    def _page_args(self, k):
        """Paged-mode page prep for one decode block (host work on the
        dispatch boundary — zero syncs): guarantee every occupied slot
        owns writable pages for its next `k` KV rows — allocating fresh
        pages and copy-on-writing shared ones (each CoW is one tiny
        pre-compiled `("page_copy",)` dispatch) — then materialize the
        page table at the current rung width. Coverage is clipped to
        the request's total row need; a frozen lane's held-position
        rewrite past that lands on the null page by construction
        (unmapped table entries are 0)."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.CACHE_PAGE)
        copy = self._exes[("page_copy",)]
        for slot, rec in self._slot_req.items():
            req = rec.req
            needed = int(req.prompt.size) + req.max_new_tokens
            hi = min(rec.disp_pos + k, needed)
            if hi <= rec.disp_pos:
                continue
            for src, dst in self._pages.ensure_range(slot, rec.disp_pos,
                                                     hi - 1):
                cache = copy(self._state[_CACHE], np.int32(src),
                             np.int32(dst))
                self._state = (cache,) + self._state[1:]
            rec.disp_pos = hi
        self._emit_page_metrics()
        return self._pages.build_table(
            self.slots, self._rung // self.decoder.page_size)

    def _emit_page_metrics(self):
        """Page-pool observability (enabled-guarded, rides the dispatch
        boundary): occupancy/sharing gauges plus eviction and
        prefix-hit counters incremented by delta from the allocator's
        monotonic stats."""
        if not _mon.enabled():
            return
        reg = _mon.get_registry()
        occ = self._pages.occupancy()
        reg.gauge(_mon.GEN_PAGES_ACTIVE,
                  help="physical KV pages holding live or cold-resident "
                       "content").set(occ["pages_active"])
        reg.gauge(_mon.GEN_PAGES_SHARED,
                  help="shared (prefix-dedup) pages referenced by >= 1 "
                       "live slot").set(occ["pages_shared"])
        st = self._pages.stats
        for metric, key, hlp in (
                (_mon.GEN_PAGE_EVICTIONS, "evictions",
                 "cold shared KV pages evicted (LRU / ladder)"),
                (_mon.GEN_PREFIX_HITS, "prefix_hits",
                 "admissions that reused >= 1 shared prefix page")):
            delta = st[key] - self._page_counts[key]
            if delta:
                reg.counter(metric, help=hlp).inc(delta)
                self._page_counts[key] = st[key]

    def _dispatch_block(self):
        """Dispatch the next decode block (superstep scan or drafting
        verify round) for the whole batch, start the ASYNC host copy of
        its sampled-token output, then deliver the PREVIOUS block while
        this one computes — the journal append and stream delivery run
        behind compute instead of gating it."""
        t0 = time.perf_counter()
        if _faults.ACTIVE is not None:
            # multi-token block dispatches (superstep scans AND
            # drafting verify rounds) fire the superstep site; the
            # k=1 per-token path keeps the original step site so
            # existing chaos schedules keep their call numbering
            _faults.ACTIVE.fire(_faults.GENERATION_SUPERSTEP
                                if self.superstep > 1 or self.draft
                                else _faults.GENERATION_STEP)
        eos, budget = self._superstep_args()
        ptab = (() if self._pages is None else
                (self._page_args(self.draft + 1 if self.draft
                                 else self.superstep),))
        if self.draft:
            draft, dlen = self._propose_drafts()
            call = self._exes[("verify", self._rung, self.draft)]
            out = call(*self._margs, *self._state, eos, budget, draft,
                       dlen, *ptab)
            k, proposed = self.draft + 1, dlen
        else:
            call = self._exes[("superstep", self._rung,
                               self.superstep)]
            out = call(*self._margs, *self._state, eos, budget, *ptab)
            k, proposed = self.superstep, None
        self._state = tuple(out[:8])
        block = self._start_fetch(out[8])
        prev, self._inflight = self._inflight, _Block(
            block, dict(self._slot_req), k, t0, time.perf_counter(),
            proposed)
        if prev is not None:
            self._deliver_block(prev)

    def _deliver_block(self, blk):
        """Materialize one sampled-token block (THE host sync) and
        deliver it step-major: -1 marks a frozen/empty lane; a slot
        retired or re-admitted since the block's dispatch is skipped
        (its journal snapshot no longer owns the slot)."""
        overlap_ms = (time.perf_counter() - blk.t_copy) * 1e3
        toks = self._fetch_tokens(blk.tokens)         # (k, S)
        dt_ms = (time.perf_counter() - blk.t0) * 1e3
        # request timelines: one "block" event per still-owned slot —
        # appended HERE, on the existing fetch boundary (toks is host
        # data already), BEFORE delivery so a retirement this block
        # lands after its final block event. Zero new syncs.
        ex_tid = None
        for slot, rec in blk.recs.items():
            if self._slot_req.get(slot) is not rec:
                continue
            if ex_tid is None and rec.expect is None:
                ex_tid = rec.req.trace_id
            tr = rec.req.trace
            if tr is not None:
                tr.event("block", k=blk.k,
                         tokens=int((toks[:, slot] >= 0).sum()),
                         wall_ms=round(dt_ms, 3),
                         overlap_ms=round(overlap_ms, 3))
        live = 0
        ndel = np.zeros((toks.shape[1],), np.int32)
        for row in toks:
            for slot, rec in blk.recs.items():
                tok = int(row[slot])
                if tok < 0 or self._slot_req.get(slot) is not rec:
                    continue
                if rec.expect is None:
                    live += 1
                ndel[slot] += 1
                self._deliver(slot, rec, tok)
        self.stats["steps"] += 1
        self.stats["tokens"] += live
        # realized block depth: a superstep block truly executed k scan
        # iterations, but a drafting round is ONE dispatch whose token
        # yield is whatever was accepted — dividing its wall by the
        # MAXIMUM deliverable (draft+1) would overstate per-token
        # latency quality by up to (draft+1)x on miss-heavy workloads
        k_real = (blk.k if blk.proposed is None
                  else max(1, int(ndel.max(initial=0))))
        self._latencies.append(dt_ms / k_real)
        accepts = rejects = 0
        if blk.proposed is not None:
            # count only tokens that actually reached delivery (ndel):
            # lanes of slots retired/re-admitted since dispatch were
            # skipped above and must not inflate the acceptance rate
            accepts = int(np.minimum(np.maximum(ndel - 1, 0),
                                     blk.proposed).sum())
            rejects = int(blk.proposed.sum()) - accepts
            self.stats["draft_accepts"] += accepts
            self.stats["draft_rejects"] += rejects
        multi = self.superstep > 1 or self.draft > 0
        if multi:
            self.stats["supersteps"] += 1
        if self._pressure:
            self._clean_steps += k_real
            if self._clean_steps >= self.pressure_relief_steps:
                self._relieve_pressure()
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GEN_TOKENS,
                        help="tokens generated (all slots)").inc(live)
            reg.histogram(_mon.GEN_PER_TOKEN_MS,
                          help="decode wall time per token (block "
                               "wall / realized block depth)").observe(
                dt_ms / k_real, trace_id=ex_tid)
            reg.histogram(_mon.GEN_TOKENS_PER_DISPATCH,
                          help="live tokens delivered per decode "
                               "dispatch").observe(live)
            reg.histogram(_mon.GEN_FETCH_OVERLAP_MS,
                          help="window the async token fetch had to "
                               "overlap the next dispatch").observe(
                overlap_ms)
            if multi:
                reg.counter(_mon.GEN_SUPERSTEPS,
                            help="multi-token decode-block dispatches "
                                 "(superstep scans / draft-verify "
                                 "rounds)").inc()
            if blk.proposed is not None:
                reg.counter(_mon.GEN_DRAFT_ACCEPTS,
                            help="draft tokens accepted (delivered "
                                 "beyond the per-round baseline "
                                 "token)").inc(accepts)
                reg.counter(_mon.GEN_DRAFT_REJECTS,
                            help="draft tokens proposed but not "
                                 "delivered (mismatch or EOS/budget "
                                 "truncation)").inc(rejects)

    def _start_fetch(self, arr):
        """Start the NON-BLOCKING device→host copy of a sampled-token
        block (part of the declared fetch boundary): the copy runs
        while the next block computes; `_fetch_tokens` later
        materializes an already-landed buffer instead of stalling the
        loop on the round-trip."""
        try:
            arr.copy_to_host_async()
        except AttributeError:      # backend without async copy:
            pass                    # _fetch_tokens blocks as before
        return arr

    def _fetch_tokens(self, arr):
        """THE per-superstep host sync: materialize the sampled-token
        block. The journal append rides this same boundary — `_deliver`
        stores the fetched tokens on the request's host-side list, so
        crash-replay costs zero extra syncs."""
        self.token_fetches += 1
        return np.asarray(arr)

    def _deliver(self, slot, rec, tok):
        req = rec.req
        if rec.expect is not None:
            # crash-replay suppression: this token was delivered before
            # the crash — verify the re-generated stream matches the
            # journal and hand delivery back to the live path once the
            # prefix is exhausted
            if tok != rec.expect[rec.replay_idx]:
                req.error = ReplayDivergedError(
                    f"replayed token {tok} != journaled "
                    f"{rec.expect[rec.replay_idx]} at position "
                    f"{rec.replay_idx}")
                rec.expect = None
                self._retire_slot(slot, "error")
                return
            rec.replay_idx += 1
            if rec.replay_idx >= len(rec.expect):
                rec.expect = None
            return
        self._consecutive_failures = 0      # forward progress
        req._push(tok)
        reason = self._finished_reason(req)
        if reason is not None:
            self._retire_slot(slot, reason)

    def _retire_slot(self, slot, reason):
        """Per-sequence retirement: clear the slot's device columns
        (one tiny pre-compiled dispatch) and free it for admission."""
        call = self._exes[("retire",)]
        pos, active, tokens = call(self._state[_POS],
                                   self._state[_ACTIVE],
                                   self._state[_TOKENS], np.int32(slot))
        self._state = (self._state[_CACHE], pos, active, tokens,
                       *self._state[_RNG:])
        rec = self._slot_req.pop(slot)
        self._free.append(slot)
        if self._pages is not None:
            # private pages free; shared prefix pages stay resident
            # cold for the next identical prompt (evictable currency)
            self._pages.release_slot(slot)
        self.stats["retirements"] += 1
        try:
            if _mon.enabled():
                reg = _mon.get_registry()
                reg.counter(_mon.GEN_RETIREMENTS,
                            help="sequences retired (EOS or "
                                 "length)").inc()
                reg.gauge(_mon.GEN_ACTIVE_SLOTS,
                          help="occupied decode slots").set(
                    len(self._slot_req))
        finally:
            # once popped from the journal, the request MUST finish —
            # a failure above would otherwise leave it unreplayable
            # and its consumer hung forever
            rec.req._finish(reason)

    # -- survivability: crash-replay, supervision, degradation -----------
    def _survive(self, exc):
        """Decode-loop failure: crash-replay recovery first (journal →
        rebuild → re-admit), then supervised restarts under the
        RetryPolicy budget. OOM-classified failures escalate the
        memory-pressure ladder before the rebuild. Returns False when
        the server latched dead (the loop must exit)."""
        self.stats["errors"] += 1
        self._consecutive_failures += 1
        if self._consecutive_failures > self.max_consecutive_failures:
            self._die(exc, reason=(
                f"no forward progress after "
                f"{self._consecutive_failures} consecutive "
                f"decode-loop failures"))
            return False
        if _mon.enabled():
            _events.emit(
                "generation", _events.SERVER_DISRUPTED,
                attrs={"error": type(exc).__name__,
                       "consecutive": self._consecutive_failures},
                correlation_id=self._corr)
        if CrashReportingUtil.is_oom(exc):
            self._note_memory_pressure(exc)
        try:
            self._recover(exc)
            if _mon.enabled():
                _events.emit("generation", _events.SERVER_RECOVERED,
                             attrs={"via": "replay"},
                             correlation_id=self._corr)
            return True
        except Exception as e2:  # noqa: BLE001 — supervisor takes over
            ok = self._supervised_restart(e2)
            if ok and _mon.enabled():
                _events.emit("generation", _events.SERVER_RECOVERED,
                             attrs={"via": "restart"},
                             correlation_id=self._corr)
            return ok

    def _recover(self, exc=None):
        """Crash-replay recovery: every in-flight journal moves to the
        replay-pending set (the donated device state is presumed
        poisoned mid-dispatch), the decode state is rebuilt at the
        smallest rung from the warm executable set, and each surviving
        request is re-admitted with its continuation bit-identical to
        an uninterrupted run. Raises when the rebuild/replay itself
        fails — the supervisor retries; pending journals survive the
        retry because re-admission is idempotent from the journal."""
        with self._lock:
            if self._shutdown or self._dead is not None:
                return
            # the pipelined block (if any) died with the state: its
            # undelivered tokens were never journaled, so replay
            # regenerates exactly them
            self._inflight = None
            for rec in self._slot_req.values():
                if rec not in self._replaying:
                    self._replaying.append(rec)
            self._slot_req.clear()
            self._free = list(range(self.slots))
            self._replaying.sort(key=lambda r: r.admit_id)
            self._rung = self.cache_lengths[0]
            self._state = self._init_state(self._rung)
            if self._pages is not None:
                # pool contents died with the state: the allocator
                # forgets everything and the ordered re-admissions
                # rebuild table + prefix registry from the journal
                self._pages.reset()
            while self._replaying:
                rec = self._replaying[0]
                if rec.req.done():
                    self._replaying.pop(0)
                    continue
                reason = self._finished_reason(rec.req)
                if reason is not None:
                    # the final token was already delivered and only
                    # the RETIREMENT was lost to the crash: finish the
                    # request instead of replaying it — a replay would
                    # generate past EOS / max_new_tokens
                    rec.req._finish(reason)
                    self._replaying.pop(0)
                    continue
                try:
                    self._replay_one(rec)
                except MemoryPressureError as e:
                    # pre-dispatch refusal (no longer fits the capped
                    # rung): fail this request, keep replaying the rest
                    rec.req._fail(e)
                    self._replaying.pop(0)
                    continue
                self._replaying.pop(0)

    def _replay_one(self, rec):
        """Re-admit one journaled request. Preferred path: re-prefill
        prompt+generated-prefix in ONE dispatch, with the admission key
        advanced past the consumed sampling splits — the next sampled
        token continues the stream exactly (decode-exactness makes the
        prefill logits equal the uninterrupted step's). When the prefix
        outgrows the prompt-bucket ladder, fall back to re-generating
        it from the original admission state with delivery suppressed —
        per-slot keys make both paths bit-identical continuations."""
        req = rec.req
        g = len(req.tokens)
        plen = int(req.prompt.size)
        needed = plen + req.max_new_tokens
        use_prefix = g and plen + g <= self.prompt_buckets[-1]
        if use_prefix:
            # the longer prefix bucket must not force a bigger cache
            # rung than the request itself needs — a crash must never
            # inflate memory (or trip the pressure cap) versus the
            # uninterrupted run; otherwise re-generate instead
            pb_prefix = next(p for p in self.prompt_buckets
                             if p >= plen + g)
            pb_orig = next(p for p in self.prompt_buckets
                           if p >= plen)
            use_prefix = (self._rung_for(needed, pb_prefix)
                          == self._rung_for(needed, pb_orig))
        if req.trace is not None:
            req.trace.event("replay",
                            mode="prefix" if use_prefix
                            else "regenerate", delivered=g)
        if use_prefix:
            prefix = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
            key = self._advance_key(self._admit_key(rec.admit_id), g)
            rec.expect = None
            rec.replay_idx = 0
            self._admit_rec(rec, prefix, key)
            live_first = True       # the prefill sampled a NEW token
        else:
            rec.expect = list(req.tokens) or None
            live_first = rec.expect is None   # g == 0: first-ever token
            rec.replay_idx = 0
            self._admit_rec(rec, req.prompt,
                            self._admit_key(rec.admit_id))
        self.stats["replays"] += 1
        if live_first:
            self.stats["tokens"] += 1
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GEN_REPLAYS,
                        help="in-flight requests re-admitted by "
                             "crash-replay").inc()
            _events.emit(
                "generation", _events.SERVER_REPLAY,
                attrs={"request": getattr(req, "trace_id", None),
                       "mode": "prefix" if use_prefix else "regenerate",
                       "delivered": g},
                correlation_id=self._corr)
            if live_first:
                reg.counter(_mon.GEN_TOKENS,
                            help="tokens generated (all slots)").inc()
            reg.gauge(_mon.GEN_ACTIVE_SLOTS,
                      help="occupied decode slots").set(
                len(self._slot_req))

    @staticmethod
    def _finished_reason(req):
        """The finish reason a delivered-but-unretired request should
        get ("eos" / "length"), or None while it still needs tokens —
        the guard that keeps crash-replay from continuing a stream
        whose terminal token already reached the consumer."""
        if req.tokens and req.eos_id is not None \
                and req.tokens[-1] == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_new_tokens:
            return "length"
        return None

    def _advance_key(self, key, n):
        """Advance an admission key past `n` consumed sampling splits —
        the replay-prefill continuation key. ONE dispatch of the
        pre-compiled `("advance_key_n",)` executable (n is a traced
        scalar), so replay performs zero live compiles and O(1)
        dispatches however long the delivered prefix."""
        return self._exes[("advance_key_n",)](key, np.int32(n))

    def _supervised_restart(self, exc):
        """Recovery failed: retry the rebuild+replay from the warm
        FunctionStore under the bounded RetryPolicy. The typed
        ServerDeadError latch only engages once the budget is
        exhausted (or the failure is classified unrestartable)."""

        def on_retry(attempt, e):
            self._count_restart()
            if CrashReportingUtil.is_oom(e):
                self._note_memory_pressure(e)

        self._count_restart()
        try:
            self.restart_policy.call(self._recover, on_retry=on_retry,
                                     label="generation-server restart")
            return True
        except Exception as final:  # noqa: BLE001 — budget exhausted
            self._die(final, reason="supervised restart budget "
                                    "exhausted")
            return False

    def _restartable(self, exc):
        """Restart classifier: anything is worth a bounded restart
        except a latched death, or an OOM once the degradation ladder
        has no smaller rung left to shrink into (another allocation
        attempt at the same size cannot help)."""
        if isinstance(exc, ServerDeadError):
            return False
        if CrashReportingUtil.is_oom(exc):
            if self._pressure < (4 if self._pages is not None else 3):
                return True
            cap = self._rung_cap or self.cache_lengths[-1]
            return any(c < cap for c in self.cache_lengths)
        return True

    def _count_restart(self):
        self.stats["restarts"] += 1
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.GEN_RESTARTS,
                help="supervised decode-loop restarts from the warm "
                     "FunctionStore").inc()
            _events.emit("generation", _events.SERVER_RESTARTED,
                         attrs={"restarts": self.stats["restarts"]},
                         correlation_id=self._corr)

    # -- memory-pressure degradation ladder -------------------------------
    def _note_memory_pressure(self, exc):
        """Escalate the ladder one level: 1 = refuse cache growth past
        the current rung, 2 = also shed every queued admission, 3 =
        shrink the cap one pre-compiled rung (in-flight requests replay
        into it; ones that no longer fit fail typed). Paged servers get
        an extra level between shed and shrink — 3 = evict every cold
        (refcount-zero) shared prefix page, reclaiming pool headroom
        before giving up rung capacity; shrink moves to 4. Keeps a
        `monitoring/memory.py` telemetry reading for OOM forensics."""
        self._clean_steps = 0
        self._pressure_ts = time.monotonic()
        if self._pressure == 0 or self._rung_cap is None:
            self._rung_cap = self._rung
        if self._pages is not None:
            ladder = ("refuse_growth", "shed_queue", "evict_pages",
                      "shrink")
        else:
            ladder = ("refuse_growth", "shed_queue", "shrink")
        self._pressure = min(len(ladder), self._pressure + 1)
        action = ladder[self._pressure - 1]
        if _mon.enabled():
            _events.emit(
                "generation", _events.PRESSURE_ESCALATED,
                attrs={"level": self._pressure, "action": action,
                       "error": type(exc).__name__},
                correlation_id=self._corr)
        if self._pressure >= 2:
            self._shed_queue(exc)
        if self._pages is not None and self._pressure >= 3:
            evicted = self._pages.evict_cold()
            if _mon.enabled():
                _events.emit("generation", _events.PAGES_EVICTED,
                             attrs={"evicted": evicted},
                             correlation_id=self._corr)
        if self._pressure >= len(ladder):
            smaller = [c for c in self.cache_lengths
                       if c < self._rung_cap]
            if smaller:
                self._rung_cap = smaller[-1]
                if _mon.enabled():
                    _events.emit("generation", _events.CACHE_SHRUNK,
                                 attrs={"cap": self._rung_cap},
                                 correlation_id=self._corr)
            else:
                # no smaller pre-compiled rung: the ladder is out of
                # moves — say so instead of reporting a phantom shrink
                action = "at_floor"
        self._count_degradation(action)
        if _mon.enabled():
            try:
                from deeplearning4j_tpu.monitoring import memory as _mem
                _mem.sample()
            except Exception:  # noqa: BLE001 — telemetry best-effort
                pass

    def _relieve_pressure(self):
        """A clean stretch of decode steps — or of wall-clock quiet —
        decays one pressure level; back at level 0 the growth cap
        lifts entirely."""
        self._clean_steps = 0
        self._pressure_ts = time.monotonic()
        self._pressure = max(0, self._pressure - 1)
        if self._pressure == 0:
            self._rung_cap = None
        if _mon.enabled():
            _events.emit("generation", _events.PRESSURE_RELIEVED,
                         attrs={"level": self._pressure},
                         correlation_id=self._corr,
                         resolves=self._pressure == 0)

    def _maybe_relieve_by_time(self):
        """Wall-clock decay: re-evaluated on every growth attempt, so
        pressure lifts even when the remaining traffic is all refused
        (no decode steps run, the step-count relief never fires)."""
        if self._pressure and self.pressure_relief_secs is not None \
                and (time.monotonic() - self._pressure_ts
                     >= self.pressure_relief_secs):
            self._relieve_pressure()

    def _check_growth(self, target):
        """Degradation-ladder gate on cache growth — PRE-dispatch, so a
        refusal is contained to the triggering request. Refuses past
        the pressure cap, and proactively when the live device-memory
        telemetry is already past the high-water mark (which also
        reports the server 'degraded' on /health while it lasts)."""
        self._maybe_relieve_by_time()
        if self._rung_cap is not None and target > self._rung_cap:
            self._count_degradation("refuse_growth")
            raise MemoryPressureError(
                f"cache growth to rung {target} refused: the "
                f"memory-pressure ladder caps the cache at rung "
                f"{self._rung_cap} (pressure level {self._pressure})")
        if self.memory_high_water is not None:
            from deeplearning4j_tpu.monitoring import memory as _mem
            for stats in _mem.device_memory_stats().values():
                if not stats:
                    continue
                used = stats.get("bytes_in_use")
                limit = stats.get("bytes_limit")
                if used and limit \
                        and used / limit > self.memory_high_water:
                    # telemetry-driven refusals are a degradation too:
                    # /health must say 'degraded' while the replica is
                    # systematically refusing growth, not 'ok'. No cap
                    # is set — growth resumes the moment the telemetry
                    # clears, and the pressure level decays on its own
                    self._pressure = max(self._pressure, 1)
                    self._pressure_ts = time.monotonic()
                    self._clean_steps = 0   # fresh pressure evidence
                    self._count_degradation("refuse_growth")
                    raise MemoryPressureError(
                        f"cache growth to rung {target} refused: "
                        f"device memory at {used / limit:.0%} of limit "
                        f"exceeds the {self.memory_high_water:.0%} "
                        f"high-water mark")

    def _shed_queue(self, cause):
        """Ladder level 2: fail every queued (not-yet-admitted) request
        typed instead of admitting into a memory-starved batch."""
        shed = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            err = MemoryPressureError(
                "queued admission shed under memory pressure")
            err.__cause__ = cause
            _queued_req(item)._fail(err)
            shed += 1
        if shed and _mon.enabled():
            _events.emit("generation", _events.SERVER_SHED,
                         attrs={"shed": shed},
                         correlation_id=self._corr)
        return shed

    def _count_degradation(self, action):
        self.stats["degradations"] += 1
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.GEN_DEGRADATIONS, labels={"action": action},
                help="memory-pressure degradation-ladder events").inc()

    def _fail_open_requests(self, err):
        """Push the terminal error sentinel to every in-flight and
        replay-pending request (caller holds the lock; already-finished
        requests keep their results) and clear both collections."""
        for rec in list(self._slot_req.values()):
            if not rec.req.done():
                rec.req._fail(err)
        self._slot_req.clear()
        for rec in self._replaying:
            if not rec.req.done():
                rec.req._fail(err)
        self._replaying.clear()

    def _drain_queue(self, err):
        while True:
            try:
                _queued_req(self._queue.get_nowait())._fail(err)
            except queue.Empty:
                return

    def _die(self, cause, reason="decode loop died"):
        """Terminal: latch the typed ServerDeadError, refuse future
        submits, and push the error sentinel to EVERY open request —
        in-flight, replay-pending, and queued — immediately, so no
        stream consumer waits out its timeout on a dead server."""
        err = ServerDeadError(f"GenerationServer {reason}: {cause!r}")
        err.__cause__ = cause
        if _mon.enabled():
            _events.emit("generation", _events.SERVER_DEAD,
                         attrs={"reason": reason,
                                "error": type(cause).__name__},
                         correlation_id=self._corr)
        with self._lock:
            self._dead = err
            self._fail_open_requests(err)
        self._drain_queue(err)

    # -- lifecycle / status ----------------------------------------------
    def shutdown(self):
        """Idempotent: stops the decode loop; in-flight, replay-pending,
        and queued requests fail with a RuntimeError."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        err = RuntimeError("GenerationServer shut down")
        # any submit racing this drain either saw _shutdown under the
        # lock (raised) or enqueued before we took it above — so after
        # this drain the queue stays empty forever
        with self._lock:
            self._fail_open_requests(err)
            self._drain_queue(err)

    def __enter__(self):
        self.warmup()
        return self

    def __exit__(self, *exc):
        self.shutdown()

    def serving_state(self):
        """Compact survivability view for `GET /health`
        (resilience.health_snapshot): dead → the replica must be
        replaced; degraded → serving under the memory-pressure ladder;
        serving/cold otherwise."""
        if self._shutdown:
            # deliberate shutdown wins over an earlier death: the
            # operator already acted, /health must not keep paging
            state = "shutdown"
        elif self._dead is not None:
            state = "dead"
        elif self._pressure:
            state = "degraded"
        else:
            state = "serving" if self._warm else "cold"
        out = {"state": state, "pressure": self._pressure,
               "rung_cap": self._rung_cap,
               "active_slots": len(self._slot_req),
               "replays": self.stats["replays"],
               "restarts": self.stats["restarts"],
               "degradations": self.stats["degradations"]}
        if self._pages is not None:
            # page-pool occupancy + dedup/CoW/eviction counters: the
            # capacity signal for paged replicas on /health and
            # /generation (status() spreads this dict)
            out["page_pool"] = {**self._pages.occupancy(),
                                **self._pages.stats}
        return out

    def _latency_percentiles(self):
        """Per-token latency p50/p99 (ms) over the recent decode
        blocks' block-wall/block-steps samples — endpoint-served even
        with monitoring disabled (the host-side ring costs one float
        append per block)."""
        if not self._latencies:
            return {"per_token_p50_ms": None, "per_token_p99_ms": None}
        p50, p99 = np.percentile(list(self._latencies), [50, 99])
        return {"per_token_p50_ms": round(float(p50), 3),
                "per_token_p99_ms": round(float(p99), 3)}

    def status(self):
        dispatches = self.stats["steps"] + self.stats["admissions"]
        return {
            "decoder": type(self.decoder).__name__,
            "slots": self.slots,
            "cache_lengths": list(self.cache_lengths),
            "rung": self._rung,
            "prompt_buckets": list(self.prompt_buckets),
            "superstep": self.superstep,
            "draft": self.draft,
            "paged": self.paged,
            "active_slots": len(self._slot_req),
            "queued": self._queue.qsize(),
            "warm": self._warm,
            "executables": len(self._exes),
            "token_fetches": self.token_fetches,
            "tokens_per_dispatch": round(
                self.stats["tokens"] / dispatches, 3) if dispatches
            else None,
            "host_syncs_per_token": round(
                self.token_fetches / self.stats["tokens"], 3)
            if self.stats["tokens"] else None,
            **self._latency_percentiles(),
            **self.serving_state(),
            **self.stats,
            "store": (None if self._store is None
                      else self._store.status()),
        }


def status():
    """Aggregate generation status for every live server
    (`GET /generation` on the UIServer)."""
    return {"servers": [s.status() for s in list(_SERVERS)]}
