"""GenerationServer — continuous-batching autoregressive serving over
the AOT executable stack.

The chat-style scenario: long-lived stateful requests share one
fixed-shape decode batch. A background decode thread runs ONE
pre-compiled executable per token for the WHOLE batch; new requests are
admitted into free slots of the in-flight batch between steps
(prefill + cache graft, one dispatch) and finished ones retire without
ever changing a shape — the executable set is closed over
(slot bucket, cache-length rung, prompt bucket) exactly like
`ParallelInference`'s bucket ladder is closed over batch shapes.

Steady-state contract (linted by scripts/check_fastpath.py and
regression-tested): past `warmup()`, the decode loop performs ZERO jit
traces and ZERO XLA compiles — step, admit, retire, and grow all
resolve from the in-memory executable tier — and the ONLY per-token
host sync is the sampled-token fetch (`_fetch_tokens`); the whole
decode state (KV caches / recurrent carries, positions, active mask,
per-slot sampling knobs, rng keys) lives on device and is DONATED
through every step, so steady state is one fixed-shape dispatch per
token.

Executables (per `FunctionStore`, two-tier: in-memory + on-disk
serialized — a restarted replica warms from disk):

- ``("step", C)`` — decode one token for all S slots at cache rung C:
  embed → write K/V row (or advance carries) → single-query attention →
  logits → fused per-slot sampling (greedy / temperature / top-k, all
  TRACED per-slot values: mixed sampling configs share one executable).
- ``("admit", C, P)`` — prefill one prompt at prompt bucket P, graft
  its cache/carry rows into a slot, arm the slot's sampling config and
  rng key, sample the first token.
- ``("retire",)`` — clear a slot's position/active/token columns
  (cache rows need no clearing: the cache-validity mask hides them).
- ``("grow_to_<C'>", C)`` — pad the KV cache from rung C to C' when an
  admission needs more room than the current rung (never shrinks
  mid-flight; recurrent carry state is rung-independent).

Resilience: admission rides the same bounded-enqueue/shed semantics as
`ParallelInference` (`InferenceOverloadedError`, enqueue timeout); a
decode-loop failure fails the affected requests, resets the device
state, and keeps serving.
"""
from __future__ import annotations

import queue
import threading
import time
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.generation.sampling import method_id, sample_step

__all__ = ["GenerationRequest", "GenerationServer", "status"]

_SERVERS = weakref.WeakSet()

#: decode-state tuple layout (everything donated through each step)
_CACHE, _POS, _ACTIVE, _TOKENS, _RNG, _METHOD, _TEMP, _TOPK = range(8)


class GenerationRequest:
    """Handle for one submitted prompt: collects generated tokens,
    streams them (`stream()` / `on_token`), resolves via `result()`."""

    def __init__(self, prompt, max_new_tokens, eos_id, method,
                 temperature, top_k, on_token=None):
        self.prompt = prompt                  # np.int32 (plen,)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.method = method                  # sampling.GREEDY/SAMPLE
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.on_token = on_token
        self.tokens = []                      # generated token ids
        self.error = None
        self.finish_reason = None             # "eos" | "length" | "error"
        self._done = threading.Event()
        self._stream = queue.Queue()

    # -- server side ------------------------------------------------------
    def _push(self, tok):
        self.tokens.append(tok)
        self._stream.put(tok)
        if self.on_token is not None:
            try:
                self.on_token(tok)
            except Exception:  # noqa: BLE001 — a bad callback must not
                pass           # kill the shared decode loop

    def _finish(self, reason):
        self.finish_reason = reason
        self._done.set()
        self._stream.put(None)

    def _fail(self, exc):
        self.error = exc
        self._finish("error")

    # -- client side ------------------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the request finished; returns the generated
        token ids — when generation stopped on `eos_id`, the EOS token
        is the last element (finish_reason tells which case hit)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation request still in flight")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def stream(self, timeout=None):
        """Yield tokens as they are generated (ends at EOS/length).
        `timeout` bounds the wait per token (TimeoutError on expiry,
        matching result())."""
        while True:
            try:
                tok = self._stream.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    "generation stream produced no token within the "
                    "timeout") from None
            if tok is None:
                if self.error is not None:
                    raise self.error
                return
            yield tok


class GenerationServer:
    """Continuous-batching KV-cache decode server over one model.

    `decoder`: a `generation.decode` adapter (BertDecoder /
    RecurrentDecoder) or a recurrent `MultiLayerNetwork` (wrapped
    automatically). `slots` is the decode batch bucket; `cache_lengths`
    the cache rungs (prompt_len + max_new_tokens must fit the top
    rung); `prompt_buckets` the prefill length ladder."""

    def __init__(self, decoder, slots=4, cache_lengths=(128,),
                 prompt_buckets=None, method="greedy", temperature=1.0,
                 top_k=0, eos_id=None, max_new_tokens=64, seed=0,
                 queue_limit=256, enqueue_timeout_ms=100.0,
                 exec_cache_dir=None):
        from deeplearning4j_tpu.generation.decode import RecurrentDecoder
        if not hasattr(decoder, "init_cache"):
            decoder = RecurrentDecoder(decoder)
        self.decoder = decoder
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        rungs = tuple(sorted({int(c) for c in cache_lengths}))
        if not rungs or rungs[0] < 2:
            raise ValueError(f"cache_lengths must be >= 2: {cache_lengths}")
        if not decoder.uses_cache_rungs:
            # carry state is O(1) in sequence length: one rung, which
            # only bounds prompt_len + max_new_tokens
            rungs = (rungs[-1],)
        if decoder.max_cache_len is not None \
                and rungs[-1] > decoder.max_cache_len:
            raise ValueError(
                f"top cache rung {rungs[-1]} exceeds the model's "
                f"maximum decodable length {decoder.max_cache_len}")
        self.cache_lengths = rungs
        if prompt_buckets is None:
            prompt_buckets, b = [], 8
            while b < rungs[-1]:
                prompt_buckets.append(b)
                b *= 2
            prompt_buckets.append(rungs[-1])
        self.prompt_buckets = tuple(sorted({int(p)
                                            for p in prompt_buckets}))
        if self.prompt_buckets[-1] > rungs[-1]:
            raise ValueError("prompt buckets cannot exceed the top "
                             "cache rung")
        self.default_method = method_id(method)
        self.default_temperature = float(temperature)
        self.default_top_k = int(top_k)
        self.default_eos_id = eos_id
        self.default_max_new_tokens = int(max_new_tokens)
        self.seed = int(seed)
        self.enqueue_timeout = float(enqueue_timeout_ms) / 1e3
        self.stats = {"tokens": 0, "steps": 0, "admissions": 0,
                      "retirements": 0, "errors": 0}
        self.token_fetches = 0       # host syncs: ONE per decode step
        self._queue = queue.Queue(maxsize=int(queue_limit))
        self._store = None           # FunctionStore, built at warmup
        self._exec_cache_dir = exec_cache_dir
        self._exes = {}              # (name, *) -> bare executable call
        self._margs = None           # non-donated model args
        self._state = None           # donated decode-state tuple
        self._rung = None
        self._slot_req = {}          # slot -> (GenerationRequest, admit#)
        self._free = list(range(self.slots))
        self._counter = 0            # admission counter (rng derivation)
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._shutdown = False
        self._dead = None            # unrecoverable decode-loop error
        self._warm = False
        self._thread = None
        _SERVERS.add(self)

    # -- warmup (the declared trace/compile boundary) ---------------------
    def warmup(self):
        """Build the whole closed executable set — step/retire per
        rung, admit per (rung, prompt bucket), grow per rung pair —
        through the two-tier FunctionStore (warm replica: deserialize,
        no XLA compile), initialize the device decode state at the
        smallest rung, and start the decode loop. Idempotent (and safe
        under concurrent first submits)."""
        with self._lock:
            return self._warmup_locked()

    def _warmup_locked(self):
        if self._warm:
            return {"compiled": 0, "from_disk": 0, "seconds": 0.0,
                    "executables": len(self._exes)}
        from deeplearning4j_tpu.runtime.executables import FunctionStore
        t0 = time.perf_counter()
        # slots is part of every executable's SHAPE but not of the
        # (name, rung, bucket) keys — it must be part of the store
        # identity or two servers over the same model with different
        # slot counts would share (wrong-shaped) disk entries
        store = FunctionStore(
            f"{self.decoder.fingerprint()}-s{self.slots}",
            directory=self._exec_cache_dir)
        store.register("step", self._traced_step,
                       donate_argnums=self._donate_range())
        store.register("admit", self._traced_admit,
                       donate_argnums=self._donate_range())
        store.register("retire", self._traced_retire,
                       donate_argnums=(0, 1, 2))
        self._margs = tuple(self.decoder.model_args())
        sds = jax.ShapeDtypeStruct
        scalar_i = sds((), jnp.int32)
        scalar_f = sds((), jnp.float32)
        for ci, rung in enumerate(self.cache_lengths):
            spec = self._state_spec(rung)
            margs_spec = jax.tree_util.tree_map(
                lambda l: sds(jnp.shape(l), jnp.result_type(l)),
                self._margs)
            key = ("step", rung)
            e = store.load_or_compile(key, (*margs_spec, *spec))
            self._exes[key] = e.call
            for p in self.prompt_buckets:
                if p > rung:
                    continue
                key = ("admit", rung, p)
                e = store.load_or_compile(
                    key, (*margs_spec, *spec, scalar_i,
                          sds((p,), jnp.int32), scalar_i,
                          sds((2,), jnp.uint32), scalar_i, scalar_f,
                          scalar_i))
                self._exes[key] = e.call
            for bigger in self.cache_lengths[ci + 1:]:
                name = f"grow_to_{bigger}"
                store.register(
                    name,
                    lambda cache, _to=bigger: self.decoder.grow(cache,
                                                                _to),
                    donate_argnums=(0,))
                key = (name, rung)
                e = store.load_or_compile(key, (spec[_CACHE],))
                self._exes[key] = e.call
        key = ("retire",)
        e = store.load_or_compile(
            key, (sds((self.slots,), jnp.int32),
                  sds((self.slots,), jnp.bool_),
                  sds((self.slots,), jnp.int32), scalar_i))
        self._exes[key] = e.call
        self._store = store
        self._rung = self.cache_lengths[0]
        self._state = self._init_state(self._rung)
        self._warm = True
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()
        return {"compiled": store.stats["compiles"],
                "from_disk": store.stats["disk_hits"],
                "seconds": time.perf_counter() - t0,
                "executables": len(self._exes)}

    def _donate_range(self):
        n = len(tuple(self.decoder.model_args()))
        return tuple(range(n, n + 8))

    def _state_spec(self, rung):
        sds = jax.ShapeDtypeStruct
        s = self.slots
        cache = jax.eval_shape(
            lambda: self.decoder.init_cache(s, rung))
        return (cache, sds((s,), jnp.int32), sds((s,), jnp.bool_),
                sds((s,), jnp.int32), sds((s, 2), jnp.uint32),
                sds((s,), jnp.int32), sds((s,), jnp.float32),
                sds((s,), jnp.int32))

    def _init_state(self, rung):
        s = self.slots
        return (self.decoder.init_cache(s, rung),
                jnp.zeros((s,), jnp.int32),
                jnp.zeros((s,), jnp.bool_),
                jnp.zeros((s,), jnp.int32),
                jnp.zeros((s, 2), jnp.uint32),
                jnp.zeros((s,), jnp.int32),
                jnp.ones((s,), jnp.float32),
                jnp.zeros((s,), jnp.int32))

    # -- traced bodies (pure; lowered once per signature at warmup) -------
    def _traced_step(self, *args):
        n = self.decoder.n_model_args
        margs = args[:n]
        cache, pos, active, tokens, rng, method, temp, topk = args[n:]
        logits, cache = self.decoder.step(margs, cache, tokens, pos)
        sampled, rng = sample_step(logits, rng, method, temp, topk)
        tokens = jnp.where(active, sampled, tokens)
        pos = jnp.where(active, pos + 1, pos)
        out = jnp.where(active, sampled, -1)
        return (cache, pos, active, tokens, rng, method, temp, topk,
                out)

    def _traced_admit(self, *args):
        n = self.decoder.n_model_args
        margs = args[:n]
        (cache, pos, active, tokens, rng, method, temp, topk,
         slot, prompt, plen, key, m, t, k) = args[n:]
        cache, logits = self.decoder.prefill(margs, cache, slot, prompt,
                                             plen)
        first, key2 = sample_step(logits[None], key[None], m[None],
                                  t[None], k[None])
        pos = pos.at[slot].set(plen)
        active = active.at[slot].set(True)
        tokens = tokens.at[slot].set(first[0])
        rng = rng.at[slot].set(key2[0])
        method = method.at[slot].set(m)
        temp = temp.at[slot].set(t)
        topk = topk.at[slot].set(k)
        return (cache, pos, active, tokens, rng, method, temp, topk,
                first[0])

    @staticmethod
    def _traced_retire(pos, active, tokens, slot):
        return (pos.at[slot].set(0),
                active.at[slot].set(False),
                tokens.at[slot].set(0))

    # -- client side ------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id="default",
               method=None, temperature=None, top_k=None, on_token=None,
               timeout_ms=None):
        """Queue one prompt for generation; returns a GenerationRequest
        immediately (tokens stream in as the decode loop reaches it).
        Admission is bounded: a full queue sheds with
        InferenceOverloadedError after the enqueue timeout."""
        from deeplearning4j_tpu.parallel.inference import bounded_enqueue
        if not self._warm:
            self.warmup()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the top prompt "
                f"bucket {self.prompt_buckets[-1]}")
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new > self.cache_lengths[-1]:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds the top cache rung {self.cache_lengths[-1]}")
        req = GenerationRequest(
            prompt, max_new,
            self.default_eos_id if eos_id == "default" else eos_id,
            self.default_method if method is None else method_id(method),
            (self.default_temperature if temperature is None
             else temperature),
            self.default_top_k if top_k is None else top_k,
            on_token=on_token)
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1e3)
        # liveness check + enqueue are ONE locked step: a request must
        # never land in the queue after shutdown()/_die() drained it
        # (nothing would ever fail or serve it — result() would hang)
        with self._lock:
            if self._shutdown:
                raise RuntimeError("GenerationServer is shut down")
            if self._dead is not None:
                raise self._dead
            bounded_enqueue(self._queue, req, deadline,
                            self.enqueue_timeout, what="generation")
        self._work.set()
        return req

    def generate(self, prompt, timeout=None, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(prompt, **kw).result(timeout=timeout)

    # -- decode loop ------------------------------------------------------
    def _loop(self):
        while not self._shutdown:
            try:
                self._admit_pending()
                if not self._slot_req:
                    if not self._work.wait(timeout=0.05):
                        continue
                    self._work.clear()
                    continue
                self._step_once()
            except Exception as e:  # noqa: BLE001 — fail reqs, stay up
                try:
                    self._recover(e)
                except Exception as e2:  # noqa: BLE001 — recovery
                    # itself failed (e.g. the state re-allocation hit
                    # the same OOM): a silent thread death would hang
                    # every future result() — mark the server dead so
                    # submit() refuses and queued requests fail
                    self._die(e2)
                    return

    def _admit_pending(self):
        """Admit queued requests into free slots of the in-flight batch
        — one prefill dispatch each, no shape changes (a longer request
        may first GROW the cache to a pre-compiled bigger rung).

        A failing admission cannot be contained to its own request:
        the grow/admit dispatch DONATES the whole decode state, so a
        post-donation failure leaves `self._state` pointing at freed
        buffers (real on TPU; CPU ignores donation) — the exception
        fails the triggering request here, then propagates so
        `_recover` fails the in-flight batch and rebuilds the state
        instead of letting the next step dispatch invalid buffers.
        (Size/shape validation already happened at submit().)"""
        while self._free:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            try:
                self._admit_one(req)
            except Exception as e:  # noqa: BLE001 — see docstring
                req._fail(e)
                raise

    def _admit_one(self, req):
        plen = int(req.prompt.size)
        pbucket = next(p for p in self.prompt_buckets if p >= plen)
        needed = plen + req.max_new_tokens
        rung = self._rung
        if needed > rung or pbucket > rung:
            rung = next(c for c in self.cache_lengths
                        if c >= needed and c >= pbucket)
            call = self._exes[(f"grow_to_{rung}", self._rung)]
            cache = call(self._state[_CACHE])
            self._state = (cache,) + self._state[1:]
            self._rung = rung
        slot = self._free.pop()
        self._counter += 1
        admit_id = self._counter
        padded = np.zeros((pbucket,), np.int32)
        padded[:plen] = req.prompt
        key = np.random.default_rng(
            (self.seed, admit_id)).integers(0, 2 ** 32, size=2,
                                            dtype=np.uint32)
        t0 = time.perf_counter()
        call = self._exes[("admit", rung, pbucket)]
        out = call(*self._margs, *self._state, np.int32(slot), padded,
                   np.int32(plen), key, np.int32(req.method),
                   np.float32(req.temperature), np.int32(req.top_k))
        self._state = tuple(out[:8])
        first = int(self._fetch_tokens(out[8]))
        prefill_ms = (time.perf_counter() - t0) * 1e3
        self._slot_req[slot] = req
        self.stats["admissions"] += 1
        self.stats["tokens"] += 1     # the prefill's first sampled token
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GEN_ADMISSIONS,
                        help="sequences admitted into the decode "
                             "batch").inc()
            reg.counter(_mon.GEN_TOKENS,
                        help="tokens generated (all slots)").inc()
            reg.histogram(_mon.GEN_PREFILL_MS,
                          help="prompt prefill + cache-graft wall "
                               "time").observe(prefill_ms)
            reg.gauge(_mon.GEN_ACTIVE_SLOTS,
                      help="occupied decode slots").set(
                len(self._slot_req))
        self._deliver(slot, req, first)

    def _step_once(self):
        """ONE token for the whole batch: a single pre-compiled
        fixed-shape dispatch; the sampled-token fetch is the only host
        sync."""
        t0 = time.perf_counter()
        call = self._exes[("step", self._rung)]
        out = call(*self._margs, *self._state)
        self._state = tuple(out[:8])
        toks = self._fetch_tokens(out[8])
        dt_ms = (time.perf_counter() - t0) * 1e3
        served = list(self._slot_req.items())
        self.stats["steps"] += 1
        self.stats["tokens"] += len(served)
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GEN_TOKENS,
                        help="tokens generated (all slots)").inc(
                len(served))
            reg.histogram(_mon.GEN_PER_TOKEN_MS,
                          help="decode-step wall time (whole "
                               "batch)").observe(dt_ms)
        for slot, req in served:
            self._deliver(slot, req, int(toks[slot]))

    def _fetch_tokens(self, arr):
        """THE per-step host sync: materialize the sampled tokens.
        Everything else stays device-resident (and donated onward)."""
        self.token_fetches += 1
        return np.asarray(arr)

    def _deliver(self, slot, req, tok):
        req._push(tok)
        if (req.eos_id is not None and tok == req.eos_id) \
                or len(req.tokens) >= req.max_new_tokens:
            self._retire_slot(
                slot, "eos" if (req.eos_id is not None
                                and tok == req.eos_id) else "length")

    def _retire_slot(self, slot, reason):
        """Per-sequence retirement: clear the slot's device columns
        (one tiny pre-compiled dispatch) and free it for admission."""
        call = self._exes[("retire",)]
        pos, active, tokens = call(self._state[_POS],
                                   self._state[_ACTIVE],
                                   self._state[_TOKENS], np.int32(slot))
        self._state = (self._state[_CACHE], pos, active, tokens,
                       *self._state[_RNG:])
        req = self._slot_req.pop(slot)
        self._free.append(slot)
        self.stats["retirements"] += 1
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.GEN_RETIREMENTS,
                        help="sequences retired (EOS or length)").inc()
            reg.gauge(_mon.GEN_ACTIVE_SLOTS,
                      help="occupied decode slots").set(
                len(self._slot_req))
        req._finish(reason)

    def _recover(self, exc):
        """A decode-loop failure fails the in-flight requests and
        resets the device state (the donated buffers may be gone
        mid-dispatch) — the server keeps serving new submissions."""
        self.stats["errors"] += 1
        with self._lock:
            for slot, req in list(self._slot_req.items()):
                req._fail(exc)
            self._slot_req.clear()
            self._free = list(range(self.slots))
            self._rung = self.cache_lengths[0]
            self._state = self._init_state(self._rung)

    def _die(self, exc):
        """Unrecoverable: record the cause, refuse future submits, and
        fail everything queued or in flight so no caller hangs on a
        server whose decode thread is gone."""
        err = RuntimeError(
            f"GenerationServer decode loop died: {exc!r}")
        err.__cause__ = exc
        with self._lock:
            self._dead = err
            for _, req in list(self._slot_req.items()):
                req._fail(err)
            self._slot_req.clear()
        while True:
            try:
                self._queue.get_nowait()._fail(err)
            except queue.Empty:
                return

    # -- lifecycle / status ----------------------------------------------
    def shutdown(self):
        """Idempotent: stops the decode loop; in-flight and queued
        requests fail with a RuntimeError."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        err = RuntimeError("GenerationServer shut down")
        # any submit racing this drain either saw _shutdown under the
        # lock (raised) or enqueued before we took it above — so after
        # this drain the queue stays empty forever
        with self._lock:
            for _, req in list(self._slot_req.items()):
                req._fail(err)
            self._slot_req.clear()
            while True:
                try:
                    self._queue.get_nowait()._fail(err)
                except queue.Empty:
                    break

    def __enter__(self):
        self.warmup()
        return self

    def __exit__(self, *exc):
        self.shutdown()

    def status(self):
        return {
            "decoder": type(self.decoder).__name__,
            "slots": self.slots,
            "cache_lengths": list(self.cache_lengths),
            "rung": self._rung,
            "prompt_buckets": list(self.prompt_buckets),
            "active_slots": len(self._slot_req),
            "queued": self._queue.qsize(),
            "warm": self._warm,
            "executables": len(self._exes),
            "token_fetches": self.token_fetches,
            **self.stats,
            "store": (None if self._store is None
                      else self._store.status()),
        }


def status():
    """Aggregate generation status for every live server
    (`GET /generation` on the UIServer)."""
    return {"servers": [s.status() for s in list(_SERVERS)]}
