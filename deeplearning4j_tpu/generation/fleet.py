"""FleetRouter — health-driven request routing across GenerationServer
replicas, with replica supervision and mid-stream failover replay.

Every per-replica hardening already exists below this layer:
crash-replay and supervised restart (generation/server.py), the
memory-pressure degradation ladder, zero-compile warm spin-up from the
shared on-disk `FunctionStore`, burn-rate SLOs (monitoring/slo.py), and
the ops event journal (monitoring/events.py). What was missing is the
COMPOSITION: one stuck decode loop was still a full outage for its
clients. The router treats replica failure as a routine, contained
event — the serving twin of the reference stack's `ParallelWrapper`
fan-out over training workers.

Routing policy (shed-to-healthy before shed-to-floor):

- Admissions go ONLY to healthy replicas — never to a dead one, never
  to one degraded under the pressure ladder, never to one whose
  per-replica burn gauge breached (multi-window burn-rate rule over
  recent request outcomes, the slo.py semantics scoped to one
  replica). A burn-breached replica receives ZERO new admissions until
  its windows stop burning.
- Among healthy replicas the least-loaded wins (active slots + queued,
  admission count as the tie-break).
- With no healthy replica but live ones remaining, the request sheds
  TYPED (`InferenceOverloadedError`) — the floor — instead of piling
  onto a replica that is already degrading.
- Only when NO live replica remains (and replacement failed or is
  exhausted) does the router latch the typed `FleetDeadError`.

Every request carries a propagated deadline and a bounded failover
budget. When a replica dies mid-stream, the router re-submits the
surviving request to a healthy replica through the server's own
journal-replay machinery (`GenerationServer.adopt`): replicas share
one seed and the router assigns fleet-wide admission ids, so a stream
is a pure function of (seed, admit id, prompt, sampling config) —
independent of WHICH replica serves it. The delivered prefix rides the
re-submission and is suppressed (prefix re-prefill or
regenerate-with-suppression, exactly like an in-process crash), so
client streams stay exactly-once and bit-identical to an uninterrupted
run (chaos-tested against a fault-free single-server baseline).

The replica supervisor runs inline in whichever relay thread first
observes a death: drain (the dead server already failed its open work;
shutdown() reaps the loop thread), then restart — the `replica.restart`
fault site fires here — by building a replacement from the replica
factory over the SAME shared exec-cache directory (warm FunctionStore:
zero live compiles), and swap it into the roster. The episode lands on
the ops journal as one ordered incident: `replica.unhealthy` (trigger)
→ `replica.drained` → `replica.replaced` (resolving), with the racing
`request.failover` events absorbed while it is open.

The router also emits an autoscale signal — queue depth x SLO burn →
desired replica count — on `GET /fleet`, the metrics plane
(`dl4j.fleet.desired_replicas`), and the cross-host replica registry
(`publish()` / `directory()` over the coordination KV's
`fleet/<process_id>` namespace).

Hot-path contract (linted by scripts/check_fastpath.py): the route /
dispatch / relay / failover walk is pure host bookkeeping — no traces,
no device syncs, and every metrics/event touch sits behind the
one-branch enabled guard. The declared cold boundary is `_supervise`
(replica replacement may warm executables from disk).
"""
from __future__ import annotations

import math
import threading
import time
import weakref
from collections import deque

import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.monitoring import requests as _req
from deeplearning4j_tpu.monitoring import slo as _slo
from deeplearning4j_tpu.generation.sampling import method_id
from deeplearning4j_tpu.generation.server import GenerationRequest
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.errors import (FleetDeadError,
                                                  InferenceOverloadedError,
                                                  InferenceTimeoutError,
                                                  MemoryPressureError,
                                                  ReplayDivergedError,
                                                  ServerDeadError,
                                                  TransientError)

__all__ = ["FleetRequest", "FleetRouter", "status", "directory"]

_ROUTERS = weakref.WeakSet()


class _BurnGauge:
    """Per-replica burn-rate health: the slo.py multi-window rule over
    recent request OUTCOMES (ok / failed) on one replica. Breached when
    both the short window (bad right now) and the long window (bad long
    enough to matter) burn faster than the error budget with at least
    `min_samples` of evidence; recovers by itself as bad samples age
    out of the windows."""

    def __init__(self, short_s, long_s, budget, min_samples):
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.budget = float(budget)
        self.min_samples = int(min_samples)
        self._samples = deque()
        self._lock = threading.Lock()

    def record(self, now, bad):
        with self._lock:
            self._samples.append((now, bool(bad)))
            while self._samples \
                    and now - self._samples[0][0] > self.long_s:
                self._samples.popleft()

    def _burn(self, window, now):
        inside = [bad for t, bad in self._samples if now - t <= window]
        if not inside:
            return 0.0
        return (sum(inside) / len(inside)) / self.budget

    def burn(self, now):
        with self._lock:
            while self._samples \
                    and now - self._samples[0][0] > self.long_s:
                self._samples.popleft()
            return (self._burn(self.short_s, now),
                    self._burn(self.long_s, now))

    def breached(self, now):
        with self._lock:
            while self._samples \
                    and now - self._samples[0][0] > self.long_s:
                self._samples.popleft()
            if len(self._samples) < self.min_samples:
                return False
            return self._burn(self.short_s, now) >= 1.0 \
                and self._burn(self.long_s, now) >= 1.0

    def reset(self):
        with self._lock:
            self._samples.clear()


class _Replica:
    """One roster slot: the live server, the factory that builds its
    replacement, routing counters, and the burn gauge."""

    def __init__(self, name, server, factory, gauge, restart_budget):
        self.name = name
        self.server = server
        self.factory = factory
        self.gauge = gauge
        self.restarts_left = int(restart_budget)
        self.lock = threading.Lock()    # serializes supervision
        self.routed = 0                 # admissions dispatched here
        self.failovers = 0              # streams that left here mid-way
        self.replacements = 0           # supervisor-built servers
        self.unhealthy_latched = False  # burn-transition event edge
        self.reviving = False           # async supervision in flight

    def health(self, now):
        """dead | unhealthy | degraded | healthy (cold counts healthy:
        the first dispatch warms it from the shared store)."""
        srv = self.server
        if srv._dead is not None or srv._shutdown:
            return "dead"
        if self.gauge.breached(now):
            return "unhealthy"
        if srv._pressure:
            return "degraded"
        return "healthy"

    def snapshot(self, now):
        srv = self.server
        bs, bl = self.gauge.burn(now)
        return {"name": self.name,
                "health": self.health(now),
                "burn_short": round(bs, 4),
                "burn_long": round(bl, 4),
                "slots": srv.slots,
                "active_slots": len(srv._slot_req),
                "queued": srv._queue.qsize(),
                "routed": self.routed,
                "failovers": self.failovers,
                "replacements": self.replacements,
                "restarts_left": self.restarts_left,
                **{k: v for k, v in srv.serving_state().items()
                   if k in ("state", "pressure", "rung_cap", "replays",
                            "restarts")}}


class FleetRequest(GenerationRequest):
    """Client handle for one fleet-routed request. The client surface
    is exactly GenerationRequest's (`tokens` / `stream()` / `result()`
    / `on_token`); underneath, a relay thread feeds it from whichever
    replica currently owns the stream — across a mid-stream failover
    the handle never notices (delivered tokens arrive exactly once, in
    order, bit-identical to an uninterrupted run)."""

    def __init__(self, prompt, max_new_tokens, eos_id, method,
                 temperature, top_k, admit_id, deadline, on_token=None):
        super().__init__(prompt, max_new_tokens, eos_id, method,
                         temperature, top_k, on_token=on_token)
        self.admit_id = int(admit_id)   # fleet-wide (rng identity)
        self.deadline = deadline        # monotonic seconds or None
        self.attempts = 0               # failovers consumed
        self.routes = []                # replica names, dispatch order


class FleetRouter:
    """Front-end spreading generation requests across N GenerationServer
    replicas (module docstring has the policy). Replicas must agree on
    seed and shape ladders — the bit-identical-failover contract.

    Parameters
    ----------
    replicas: pre-built GenerationServer list, or None to build
        `num_replicas` via `factory(i)`.
    factory: callable(index) -> GenerationServer; also the supervisor's
        replacement builder (point it at the SAME exec_cache_dir so a
        replacement warms from disk with zero live compiles).
    failover_budget: mid-stream re-routes a single request may consume.
    restart_budget: replacement servers the supervisor may build per
        roster slot before that slot stays dead.
    health_windows / health_budget / health_min_samples: the
        per-replica burn gauge (short_s, long_s) / error budget /
        evidence floor.
    default_timeout_ms: deadline applied when submit() gets none.
    max_replicas: cap for the autoscale signal (None = uncapped).
    clock: injectable monotonic clock (tests age burn windows with it).
    """

    def __init__(self, replicas=None, factory=None, num_replicas=None,
                 failover_budget=2, restart_budget=2,
                 health_windows=(5.0, 20.0), health_budget=0.25,
                 health_min_samples=4, default_timeout_ms=None,
                 max_replicas=None, clock=time.monotonic):
        if replicas is None:
            if factory is None or num_replicas is None:
                raise ValueError(
                    "pass replicas=[...] or factory= with num_replicas=")
            replicas = [factory(i) for i in range(int(num_replicas))]
        replicas = list(replicas)
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        t = replicas[0]
        for srv in replicas[1:]:
            if (srv.seed, srv.cache_lengths, srv.prompt_buckets) \
                    != (t.seed, t.cache_lengths, t.prompt_buckets):
                raise ValueError(
                    "replicas must share seed, cache_lengths and "
                    "prompt_buckets — failover continuations are only "
                    "bit-identical across aligned replicas")
        self._template = t
        self.failover_budget = int(failover_budget)
        self.default_timeout_ms = default_timeout_ms
        self.max_replicas = (None if max_replicas is None
                             else int(max_replicas))
        self._clock = clock
        self._hw = (float(health_windows[0]), float(health_windows[1]))
        self._hb = float(health_budget)
        self._hm = int(health_min_samples)
        self._replicas = [
            _Replica(f"r{i}", srv,
                     (None if factory is None
                      else (lambda idx=i: factory(idx))),
                     _BurnGauge(self._hw[0], self._hw[1], self._hb,
                                self._hm),
                     restart_budget)
            for i, srv in enumerate(replicas)]
        self.stats = {"submitted": 0, "completed": 0, "failed": 0,
                      "failovers": 0, "shed": 0, "replacements": 0}
        self._counter = 0               # fleet-wide admission ids
        self._lock = threading.Lock()
        self._threads = set()
        self._dead = None               # FleetDeadError once latched
        self._closing = False
        self._corr = "fleet-%x" % id(self)   # ops-event incident key
        _ROUTERS.add(self)

    # -- client surface ---------------------------------------------------
    def warmup(self):
        """Warm every replica. Over a shared exec-cache directory the
        first replica pays the compiles and the rest deserialize."""
        return [r.server.warmup() for r in self._replicas]

    def submit(self, prompt, max_new_tokens=None, eos_id="default",
               method=None, temperature=None, top_k=None, on_token=None,
               timeout_ms=None):
        """Route one prompt into the fleet; returns a FleetRequest
        immediately. Validation mirrors GenerationServer.submit against
        the shared replica shape ladders; the fleet admission id is
        assigned HERE, in submission order, so the workload's streams
        are reproducible whatever the replica count."""
        if self._dead is not None:
            raise self._dead
        if self._closing:
            raise RuntimeError("FleetRouter is shut down")
        t = self._template
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size > t.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the top prompt "
                f"bucket {t.prompt_buckets[-1]}")
        max_new = (t.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new > t.cache_lengths[-1]:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds the top cache rung {t.cache_lengths[-1]}")
        tmo = self.default_timeout_ms if timeout_ms is None \
            else timeout_ms
        deadline = (None if tmo is None
                    else self._clock() + float(tmo) / 1e3)
        with self._lock:
            if self._dead is not None:
                raise self._dead
            self._counter += 1
            admit_id = self._counter
            self.stats["submitted"] += 1
        freq = FleetRequest(
            prompt, max_new,
            t.default_eos_id if eos_id == "default" else eos_id,
            t.default_method if method is None else method_id(method),
            t.default_temperature if temperature is None else temperature,
            t.default_top_k if top_k is None else top_k,
            admit_id, deadline, on_token=on_token)
        freq.trace = _req.start("fleet", meta={
            "prompt_len": int(prompt.size),
            "max_new_tokens": max_new,
            "admit_id": admit_id})
        if freq.trace is not None:
            freq.trace_id = freq.trace.trace_id
        th = threading.Thread(target=self._serve, args=(freq,),
                              name=f"fleet-relay-{admit_id}",
                              daemon=True)
        self._threads.add(th)
        th.start()
        return freq

    def generate(self, prompt, timeout=None, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(prompt, **kw).result(timeout=timeout)

    # -- relay loop (one thread per in-flight request) --------------------
    def _serve(self, freq):
        try:
            while not freq.done():
                try:
                    replica = self._route(freq)
                except Exception as e:  # noqa: BLE001 — typed refusal
                    self._finalize(freq, e)
                    return
                err = None
                try:
                    backend = self._dispatch(replica, freq)
                except Exception as e:  # noqa: BLE001 — classify below
                    err = e
                else:
                    err = self._relay(replica, freq, backend)
                    if err is None:
                        return          # finished; _relay closed it
                if not self._failover(freq, replica, err):
                    return
        except Exception as e:  # noqa: BLE001 — never strand a client
            if not freq.done():
                freq._fail(e)
        finally:
            self._threads.discard(threading.current_thread())

    def _route(self, freq):
        """Pick the healthy replica to serve `freq` (least loaded,
        admission count breaks ties). No healthy replica: supervise the
        corpses (replacement may restore one synchronously), then shed
        typed while live replicas remain — `FleetDeadError` latches
        only at zero live replicas."""
        while True:
            if self._closing:
                raise RuntimeError("FleetRouter is shut down")
            if self._dead is not None:
                raise self._dead
            now = self._clock()
            best = best_load = None
            dead = []
            alive = 0
            for r in self._replicas:
                h = self._health(r, now)
                if h == "dead":
                    dead.append(r)
                    continue
                alive += 1
                if h != "healthy":
                    continue
                load = (len(r.server._slot_req)
                        + r.server._queue.qsize(), r.routed)
                if best is None or load < best_load:
                    best, best_load = r, load
            if best is not None:
                if dead:
                    # healthy capacity remains: revive the corpses OFF
                    # the dispatch path (replacement builds block on
                    # warmup) — an idle replica's death must not wait
                    # for the fleet to drain before it is replaced
                    self._kick_supervision(dead)
                return best
            progressed = False
            for r in dead:
                cause = r.server._dead \
                    or RuntimeError("replica shut down")
                if self._supervise(r, cause):
                    progressed = True
            if progressed:
                continue
            if alive:
                with self._lock:
                    self.stats["shed"] += 1
                raise InferenceOverloadedError(
                    "fleet shed: no healthy replica "
                    "(remaining replicas degraded or burn-breached)")
            self._latch(FleetDeadError(
                "no live replica remains and replacement is exhausted"))
            raise self._dead

    def _dispatch(self, replica, freq):
        """Hand `freq` to `replica` through the adopt hook: a fresh
        backend request under the request's FLEET admission id, carrying
        the delivered prefix (failover) for journal-replay suppression.
        The `router.dispatch` chaos site fires first — an injected
        fault here must be absorbed by the failover budget."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.ROUTER_DISPATCH)
        remaining = None
        if freq.deadline is not None:
            remaining = (freq.deadline - self._clock()) * 1e3
            if remaining <= 0:
                raise InferenceTimeoutError(
                    "fleet request deadline expired before dispatch")
        backend = GenerationRequest(
            freq.prompt, freq.max_new_tokens, freq.eos_id, freq.method,
            freq.temperature, freq.top_k)
        backend.tokens = list(freq.tokens)
        replica.server.adopt(backend, freq.admit_id,
                             timeout_ms=remaining)
        replica.routed += 1
        freq.routes.append(replica.name)
        if freq.trace is not None:
            freq.trace.event("route", replica=replica.name,
                             attempt=freq.attempts + 1,
                             delivered=len(freq.tokens))
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.FLEET_ROUTED, labels={"replica": replica.name},
                help="fleet admissions dispatched per replica").inc()
        return backend

    def _relay(self, replica, freq, backend):
        """Pump the backend stream into the client handle. Returns None
        once the stream finished (the fleet request is closed), or the
        terminal exception for `_failover` to classify. The backend was
        seeded with the delivered prefix, so only NEW tokens ever
        arrive here — exactly-once needs no bookkeeping."""
        per_tok = None
        if freq.deadline is not None:
            per_tok = max(1e-3, freq.deadline - self._clock())
        try:
            for tok in backend.stream(timeout=per_tok):
                freq._push(tok)
        except Exception as e:  # noqa: BLE001 — classified by caller
            return e
        self._mark(replica, ok=True)
        with self._lock:
            self.stats["completed"] += 1
        freq._finish(backend.finish_reason)
        return None

    def _failover(self, freq, replica, exc):
        """One consumed attempt: mark the replica's gauge, supervise it
        if it died, and decide — re-route (True) within the budget and
        deadline, or fail the request typed (False)."""
        self._mark(replica, ok=False)
        replica.failovers += 1
        if isinstance(exc, ServerDeadError):
            self._supervise(replica, exc)
        if isinstance(exc, TimeoutError) and \
                not isinstance(exc, InferenceTimeoutError):
            # stream stall past the deadline: per-token waits are cut
            # to the remaining budget, so this IS deadline exhaustion
            err = InferenceTimeoutError(
                "fleet request deadline expired mid-stream")
            err.__cause__ = exc
            self._finalize(freq, err)
            return False
        expired = freq.deadline is not None \
            and self._clock() >= freq.deadline
        if expired or not self._retryable(exc, replica) \
                or freq.attempts >= self.failover_budget:
            self._finalize(freq, exc)
            return False
        freq.attempts += 1
        with self._lock:
            self.stats["failovers"] += 1
        if freq.trace is not None:
            freq.trace.event("failover", from_replica=replica.name,
                             attempt=freq.attempts,
                             delivered=len(freq.tokens),
                             error=type(exc).__name__)
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.FLEET_FAILOVERS,
                help="mid-stream request re-routes via journal "
                     "replay").inc()
            _events.emit(
                "fleet", _events.REQUEST_FAILOVER,
                attrs={"from": replica.name,
                       "delivered": len(freq.tokens),
                       "attempt": freq.attempts,
                       "error": type(exc).__name__,
                       "request": freq.trace_id},
                correlation_id=self._corr)
        return True

    @staticmethod
    def _retryable(exc, replica):
        """Failover classifier: replica-scoped failures re-route
        (another replica continues the stream bit-identically); a
        purity violation or a client error never does."""
        if isinstance(exc, ReplayDivergedError):
            return False
        if isinstance(exc, (ServerDeadError, TransientError,
                            InferenceOverloadedError,
                            MemoryPressureError)):
            return True
        # a dispatch that raced the supervisor's drain of this replica
        return isinstance(exc, RuntimeError) and replica.server._shutdown

    def _finalize(self, freq, exc):
        with self._lock:
            self.stats["failed"] += 1
        if not freq.done():
            freq._fail(exc)

    def _mark(self, replica, ok):
        replica.gauge.record(self._clock(), bad=not ok)

    def _health(self, replica, now):
        """Replica health for routing, with the burn-transition event
        (one `replica.unhealthy` per breach episode) on the edge."""
        h = replica.health(now)
        if h == "unhealthy" and not replica.unhealthy_latched:
            replica.unhealthy_latched = True
            if _mon.enabled():
                _events.emit(
                    "fleet", _events.REPLICA_UNHEALTHY,
                    attrs={"replica": replica.name,
                           "reason": "burn_rate"},
                    correlation_id=self._corr)
        elif h == "healthy" and replica.unhealthy_latched:
            replica.unhealthy_latched = False
        return h

    # -- replica supervision (the declared cold boundary) -----------------
    def _kick_supervision(self, dead):
        """Spawn (at most) one background reviver per dead replica so
        an idle replica's death is repaired while the survivors keep
        serving. The flag check races benignly: `_supervise` serializes
        on the replica lock and no-ops once the slot is live again."""
        for r in dead:
            if r.factory is None or r.restarts_left <= 0 or r.reviving:
                continue
            r.reviving = True
            threading.Thread(target=self._revive, args=(r,),
                             daemon=True,
                             name=f"fleet-revive-{r.name}").start()

    def _revive(self, replica):
        try:
            cause = replica.server._dead \
                or RuntimeError("replica shut down")
            self._supervise(replica, cause)
        finally:
            replica.reviving = False

    def _supervise(self, replica, cause):
        """Drain a dead replica and build its replacement from the
        factory over the shared FunctionStore (zero live compiles when
        the disk tier is warm). Runs inline in the first relay thread
        that observed the death (or in a background reviver thread for
        idle deaths), serialized per replica; returns True
        when the roster slot holds a live server again. An exhausted
        restart budget (or a failed replacement — the `replica.restart`
        chaos site fires just before the build) leaves the slot dead;
        the fleet latches only when EVERY slot is."""
        with replica.lock:
            srv = replica.server
            if srv._dead is None and not srv._shutdown:
                return True             # someone already replaced it
            mon_on = _mon.enabled()
            if mon_on:
                _events.emit(
                    "fleet", _events.REPLICA_UNHEALTHY,
                    attrs={"replica": replica.name, "reason": "dead",
                           "error": type(cause).__name__},
                    correlation_id=self._corr)
            open_slots = len(srv._slot_req)
            srv.shutdown()              # idempotent: reap loop thread
            if mon_on:
                _events.emit(
                    "fleet", _events.REPLICA_DRAINED,
                    attrs={"replica": replica.name,
                           "open_requests": open_slots},
                    correlation_id=self._corr)
            if replica.factory is None or replica.restarts_left <= 0:
                return False
            replica.restarts_left -= 1
            try:
                if _faults.ACTIVE is not None:
                    _faults.ACTIVE.fire(_faults.REPLICA_RESTART)
                fresh = replica.factory()
                warm = fresh.warmup()
            except Exception:  # noqa: BLE001 — slot stays dead; the
                return False   # fleet keeps serving on the survivors
            with self._lock:
                replica.server = fresh
            replica.gauge.reset()
            replica.unhealthy_latched = False
            replica.replacements += 1
            with self._lock:
                self.stats["replacements"] += 1
            if mon_on:
                reg = _mon.get_registry()
                reg.counter(
                    _mon.FLEET_REPLACEMENTS,
                    help="replacement replicas built by the fleet "
                         "supervisor").inc()
                _events.emit(
                    "fleet", _events.REPLICA_REPLACED,
                    attrs={"replica": replica.name,
                           "compiled": warm.get("compiled"),
                           "from_disk": warm.get("from_disk")},
                    correlation_id=self._corr)
            return True

    def _latch(self, err):
        with self._lock:
            if self._dead is None:
                self._dead = err
                if _mon.enabled():
                    _events.emit(
                        "fleet", _events.SERVER_DEAD,
                        attrs={"reason": "no live replica remains"},
                        correlation_id=self._corr)

    # -- autoscale / registry / status ------------------------------------
    def autoscale(self):
        """The autoscale signal: desired replica count from queue depth
        x SLO burn. Utilization is (active + queued) / total slots over
        live replicas; the burn factor is the worst breached
        objective's short-window burn from the installed SloTracker.
        Pull-path only (`/fleet`, status(), publish())."""
        now = self._clock()
        live = healthy = depth = slots = 0
        for r in self._replicas:
            h = r.health(now)
            if h == "dead":
                continue
            live += 1
            if h == "healthy":
                healthy += 1
            slots += r.server.slots
            depth += len(r.server._slot_req) + r.server._queue.qsize()
        utilization = (depth / slots) if slots else 0.0
        burn = 1.0
        tracker = _slo.ACTIVE
        if tracker is not None:
            try:
                snap = tracker.snapshot()
                for o in snap.get("objectives", {}).values():
                    if o.get("breached"):
                        burn = max(burn, float(o.get("burn_short")
                                               or 1.0))
            except Exception:  # noqa: BLE001 — signal must not raise
                pass
        if live:
            desired = max(1, math.ceil(live * utilization * burn))
        else:
            desired = max(1, len(self._replicas))
        if self.max_replicas is not None:
            desired = min(desired, self.max_replicas)
        out = {"queue_depth": depth, "slots": slots,
               "utilization": round(utilization, 4),
               "slo_burn": round(burn, 4),
               "replicas_live": live, "replicas_healthy": healthy,
               "desired_replicas": desired}
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.gauge(_mon.FLEET_HEALTHY,
                      help="replicas currently admitting "
                           "traffic").set(healthy)
            reg.gauge(_mon.FLEET_DESIRED_REPLICAS,
                      help="autoscale signal: queue depth x SLO burn "
                           "-> replica count").set(desired)
        return out

    def fleet_state(self):
        """Compact survivability view for `GET /health`
        (resilience.health_snapshot): dead → the fleet latched
        `FleetDeadError`; degraded → at least one replica is out of
        the healthy pool; serving otherwise."""
        now = self._clock()
        healths = [r.health(now) for r in self._replicas]
        if self._dead is not None:
            state = "dead"
        elif all(h == "healthy" for h in healths):
            state = "serving"
        else:
            state = "degraded"
        return {"state": state,
                "replicas": dict(zip((r.name for r in self._replicas),
                                     healths)),
                "desired_replicas": self.autoscale()["desired_replicas"]}

    def status(self):
        now = self._clock()
        return {"replicas": [r.snapshot(now) for r in self._replicas],
                "failover_budget": self.failover_budget,
                "dead": self._dead is not None,
                "autoscale": self.autoscale(),
                **self.stats}

    def publish(self, coordinator=None):
        """Publish this process's replica registry entry
        (`fleet/<process_id>` on the coordination KV) — the cross-host
        half of the roster. Returns the published document (None
        without a coordinator)."""
        coord = coordinator
        if coord is None:
            from deeplearning4j_tpu.parallel import coordination as _co
            coord = _co.ACTIVE
        if coord is None:
            return None
        now = self._clock()
        doc = {"process_id": coord.process_id,
               "replicas": [r.snapshot(now) for r in self._replicas],
               "autoscale": self.autoscale()}
        coord.publish_json(f"fleet/{coord.process_id}", doc)
        return doc

    # -- lifecycle --------------------------------------------------------
    def shutdown(self):
        """Idempotent: stop routing, shut every replica down (their
        open backends fail; relay threads surface that to clients) and
        reap the relay threads."""
        self._closing = True
        for r in self._replicas:
            try:
                r.server.shutdown()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        for th in list(self._threads):
            th.join(timeout=5)

    def __enter__(self):
        self.warmup()
        return self

    def __exit__(self, *exc):
        self.shutdown()


def status():
    """Aggregate fleet status for every live router
    (`GET /fleet` on the UIServer)."""
    return {"routers": [r.status() for r in list(_ROUTERS)]}


def directory(coordinator=None):
    """The merged cross-host replica registry: every process's
    published `fleet/<process_id>` document keyed by process id."""
    coord = coordinator
    if coord is None:
        from deeplearning4j_tpu.parallel import coordination as _co
        coord = _co.ACTIVE
    if coord is None:
        return {}
    return coord.fetch_json_dir("fleet/")
