"""Autoregressive generation: KV-cache decode with continuous-batching
admission (ROADMAP item 2 — the chat-style serving scenario class).

- `decode`   — incremental decode-mode forwards: BertDecoder (per-layer
  K/V caches + flash-attention decode kernel) and RecurrentDecoder
  (LSTM/GRU carry state, bit-identical to the full-sequence scan).
- `sampling` — fused batched greedy / temperature / top-k sampling over
  explicit per-slot rng keys (all knobs traced: no recompiles).
- `server`   — GenerationServer: fixed-shape decode batches, AOT
  executables per (slot bucket, cache rung, prompt bucket), per-slot
  admission/retirement, streaming token callbacks.
- `fleet`    — FleetRouter: health-driven routing across N replicas
  with replica supervision, mid-stream failover replay (client streams
  stay exactly-once and bit-identical), and an autoscale signal.

Quick start:

    from deeplearning4j_tpu.generation import GenerationServer
    srv = GenerationServer(net, slots=8, cache_lengths=[256],
                           method="top_k", top_k=40, temperature=0.8)
    srv.warmup()                       # closed executable set, AOT
    req = srv.submit(prompt_ids, max_new_tokens=100,
                     on_token=lambda t: print(t))
    tokens = req.result()
"""
from deeplearning4j_tpu.generation.decode import (BertDecoder,
                                                  RecurrentDecoder)
from deeplearning4j_tpu.generation.fleet import FleetRequest, FleetRouter
from deeplearning4j_tpu.generation.sampling import (GREEDY, SAMPLE,
                                                    method_id,
                                                    sample_step)
from deeplearning4j_tpu.generation.server import (GenerationRequest,
                                                  GenerationServer,
                                                  status)

__all__ = [
    "BertDecoder", "RecurrentDecoder",
    "FleetRequest", "FleetRouter",
    "GREEDY", "SAMPLE", "method_id", "sample_step",
    "GenerationRequest", "GenerationServer", "status",
]
