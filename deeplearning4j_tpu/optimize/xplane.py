"""XPlane (jax.profiler / XLA device trace) reader.

≡ the reference's SystemInfo/profiling analysis surface (deeplearning4j-core
:: util.ModelSerializer-adjacent perf tooling; nd4j OpExecutioner profiling
mode): turns the xplane.pb protobuf written by `jax.profiler.trace` /
ProfilerListener into per-op time tables, with no tensorboard/tensorflow
dependency — the wire decoding rides the same minimal protobuf codec as the
TF frozen-graph importer (autodiff/tfproto.py).

Field numbers follow tensorflow/tsl/profiler/protobuf/xplane.proto:
  XSpace.planes = 1
  XPlane: id=1, name=2, lines=3, event_metadata=4 (map), stat_metadata=5
  XLine:  id=1, name=2, timestamp_ns=3, events=4
  XEvent: metadata_id=1, offset_ps=2, duration_ps=3, stats=4
  XEventMetadata: id=1, name=2, display_name=3, stats=5
  XStat:  metadata_id=1, double=2, uint64=3, int64=4, str=5, bytes=6, ref=7
  XStatMetadata: id=1, name=2

XStats carry XLA's per-op cost-analysis metrics ("bytes accessed",
"flops", "memory_bandwidth", occupancy...) — memory_breakdown() turns
them into the per-op bytes table the round-3 HBM-bound analysis needed.

Usage:
  rows = op_breakdown("/tmp/trace")        # aggregated per-op-name
  for name, ms, n in rows[:20]: print(f"{ms:8.2f} ms  x{n:<4d} {name}")
"""
from __future__ import annotations

import glob
import os
import struct

from deeplearning4j_tpu.autodiff.tfproto import _signed, parse_fields


def _decode_stat(raw, stat_metas):
    """XStat bytes -> (name, value). The oneof: double(2)/uint64(3)/
    int64(4)/str(5)/bytes(6)/ref(7 — index into stat_metadata)."""
    f = parse_fields(raw)
    name = stat_metas.get(f.get(1, [0])[0], str(f.get(1, [0])[0]))
    if 2 in f:
        return name, struct.unpack("<d", f[2][0])[0]
    if 3 in f:
        return name, f[3][0]
    if 4 in f:
        return name, _signed(f[4][0])
    if 5 in f:
        return name, f[5][0].decode("utf-8", "replace")
    if 6 in f:
        return name, f[6][0]
    if 7 in f:
        return name, stat_metas.get(f[7][0], str(f[7][0]))
    return name, None


def _decode_map_entry(buf):
    """protobuf map<int64, Message> entry -> (key, value_bytes)."""
    f = parse_fields(buf)
    key = f.get(1, [0])[0]
    val = f.get(2, [b""])[0]
    return key, val


def find_xplane_files(trace_dir):
    """All xplane.pb files under a jax.profiler trace directory."""
    return sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.xplane.pb")))


def parse_xspace(path, with_stats=False, plane_substr=None):
    """xplane.pb -> list of planes:
    {"name": str, "lines": [{"name": str, "timestamp_ns": int,
    "events": [(meta_name, duration_ps, offset_ps)]}]}.

    with_stats=True appends a 4th element to each event tuple — a
    {stat_name: value} dict decoded from the event's XStats merged with
    its event-METADATA stats (XLA puts static cost-analysis numbers like
    "bytes accessed"/"flops" on the metadata, per-occurrence values on
    the event). plane_substr skips non-matching planes BEFORE any event
    decoding (host/thread planes dwarf the device plane in real traces)."""
    with open(path, "rb") as f:
        space = parse_fields(f.read())
    planes = []
    for praw in space.get(1, []):
        pf = parse_fields(praw)
        name = pf.get(2, [b""])[0].decode("utf-8", "replace")
        if plane_substr is not None and \
                plane_substr.lower() not in name.lower():
            continue
        stat_metas = {}
        for mraw in pf.get(5, []):
            k, v = _decode_map_entry(mraw)
            mf = parse_fields(v)
            stat_metas[k] = mf.get(2, [b""])[0].decode("utf-8", "replace")
        metas = {}
        meta_stats = {}
        for mraw in pf.get(4, []):
            k, v = _decode_map_entry(mraw)
            mf = parse_fields(v)
            metas[k] = mf.get(2, [b""])[0].decode("utf-8", "replace")
            # display_name (3) is the prettier name when present
            disp = mf.get(3, [b""])[0]
            if disp:
                metas[k] = disp.decode("utf-8", "replace")
            if with_stats and 5 in mf:
                meta_stats[k] = dict(
                    _decode_stat(s, stat_metas) for s in mf[5])
        lines = []
        for lraw in pf.get(3, []):
            lf = parse_fields(lraw)
            lname = lf.get(2, [b""])[0].decode("utf-8", "replace")
            ts_ns = lf.get(3, [0])[0]
            events = []
            for eraw in lf.get(4, []):
                ef = parse_fields(eraw)
                mid = ef.get(1, [0])[0]
                off = ef.get(2, [0])[0]
                dur = ef.get(3, [0])[0]
                if with_stats:
                    stats = dict(meta_stats.get(mid, {}))
                    for sraw in ef.get(4, []):
                        sk, sv = _decode_stat(sraw, stat_metas)
                        stats[sk] = sv
                    events.append((metas.get(mid, str(mid)), dur, off,
                                   stats))
                else:
                    events.append((metas.get(mid, str(mid)), dur, off))
            lines.append({"name": lname, "timestamp_ns": ts_ns,
                          "events": events})
        planes.append({"name": name, "lines": lines})
    return planes


def memory_breakdown(trace_dir, device_substr="TPU", line_substr=None):
    """Per-op bytes-accessed table from the XStat cost-analysis metrics:
    [(op_name, total_ms, bytes_accessed, GB_per_s)] sorted by bytes
    descending. Rides the same plane/line selection as op_breakdown; ops
    with no bytes stat report 0 (fusion roots carry the stat on TPU)."""
    totals, nbytes = {}, {}
    for line in _selected_lines(trace_dir, device_substr, line_substr,
                                with_stats=True):
        for ev in line["events"]:
            name, dur, stats = ev[0], ev[1], ev[3]
            b = 0
            for k, v in stats.items():
                if "bytes" in k.lower() and isinstance(v, int):
                    b = max(b, v)
            totals[name] = totals.get(name, 0) + dur
            nbytes[name] = nbytes.get(name, 0) + b
    rows = []
    for n, b in nbytes.items():
        ms = totals[n] / 1e9
        gbps = (b / 1e9) / (ms / 1e3) if ms > 0 else 0.0
        rows.append((n, ms, b, gbps))
    rows.sort(key=lambda r: -r[2])
    return rows


def _selected_lines(trace_dir, device_substr, line_substr, with_stats):
    """Shared plane/line selection for the breakdown tables.

    `device_substr` picks the device planes ("TPU", "GPU", or "" for
    CPU-only traces where XLA ops land on host-thread planes).
    `line_substr` picks activity lines within a plane; the default (None)
    uses the serialized "XLA Ops" line when the plane has one — summing
    every line would double-count, since "Steps" / "XLA Modules" /
    "Async XLA Ops" events span the same wall time — and otherwise
    falls back to all lines (CPU traces have per-thread lines instead)."""
    for path in find_xplane_files(trace_dir):
        for plane in parse_xspace(path, with_stats=with_stats,
                                  plane_substr=device_substr or None):
            lines = plane["lines"]
            if line_substr is not None:
                lines = [l for l in lines if line_substr in l["name"]]
            elif any(l["name"] == "XLA Ops" for l in lines):
                lines = [l for l in lines if l["name"] == "XLA Ops"]
            yield from lines


def op_breakdown(trace_dir, device_substr="TPU", line_substr=None):
    """Aggregate device-plane op durations across a trace directory.

    Returns [(op_name, total_ms, count)] sorted by total time descending;
    see _selected_lines for the plane/line selection rules."""
    totals, counts = {}, {}
    for line in _selected_lines(trace_dir, device_substr, line_substr,
                                with_stats=False):
        for name, dur, _off in line["events"]:
            totals[name] = totals.get(name, 0) + dur
            counts[name] = counts.get(name, 0) + 1
    rows = [(n, t / 1e9, counts[n]) for n, t in totals.items()]
    rows.sort(key=lambda r: -r[1])
    return rows


def print_breakdown(trace_dir, top=25, device_substr="TPU",
                    line_substr=None, out=print):
    rows = op_breakdown(trace_dir, device_substr, line_substr)
    total = sum(r[1] for r in rows)
    out(f"device total: {total:.2f} ms across {len(rows)} distinct ops")
    for name, ms, n in rows[:top]:
        out(f"{ms:9.3f} ms  x{n:<5d} {name[:90]}")
    return rows


def to_chrome_trace(trace_dir, out_path, max_events=200000):
    """Convert a jax.profiler trace directory into Chrome trace-event JSON
    (open in chrome://tracing or ui.perfetto.dev — no TensorBoard needed;
    ≡ the timeline view role of the reference's UI training dashboard).

    One pid per XPlane, one tid per XLine; complete ('X') events with
    microsecond timestamps. Returns the number of events written."""
    import json

    events = []
    pid = 0
    full = False
    for path in find_xplane_files(trace_dir):
        if full:
            break
        for plane in parse_xspace(path):
            if full:
                break
            pid += 1
            events.append({"ph": "M", "pid": pid, "name": "process_name",
                           "args": {"name": plane["name"]}})
            for tid, line in enumerate(plane["lines"], 1):
                if full:
                    break
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": line["name"]}})
                base_us = line["timestamp_ns"] / 1e3
                for name, dur, off in line["events"]:
                    if len(events) >= max_events:
                        full = True
                        break
                    events.append({
                        "ph": "X", "pid": pid, "tid": tid,
                        "name": name.split(" = ")[0].lstrip("%"),
                        "ts": base_us + off / 1e6,   # ps -> us
                        "dur": max(dur / 1e6, 0.001),
                    })
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)
