"""XPlane (jax.profiler / XLA device trace) reader.

≡ the reference's SystemInfo/profiling analysis surface (deeplearning4j-core
:: util.ModelSerializer-adjacent perf tooling; nd4j OpExecutioner profiling
mode): turns the xplane.pb protobuf written by `jax.profiler.trace` /
ProfilerListener into per-op time tables, with no tensorboard/tensorflow
dependency — the wire decoding rides the same minimal protobuf codec as the
TF frozen-graph importer (autodiff/tfproto.py).

Field numbers follow tensorflow/tsl/profiler/protobuf/xplane.proto:
  XSpace.planes = 1
  XPlane: id=1, name=2, lines=3, event_metadata=4 (map), stat_metadata=5
  XLine:  id=1, name=2, timestamp_ns=3, events=4
  XEvent: metadata_id=1, offset_ps=2, duration_ps=3
  XEventMetadata: id=1, name=2, display_name=3

Usage:
  rows = op_breakdown("/tmp/trace")        # aggregated per-op-name
  for name, ms, n in rows[:20]: print(f"{ms:8.2f} ms  x{n:<4d} {name}")
"""
from __future__ import annotations

import glob
import os

from deeplearning4j_tpu.autodiff.tfproto import parse_fields


def _decode_map_entry(buf):
    """protobuf map<int64, Message> entry -> (key, value_bytes)."""
    f = parse_fields(buf)
    key = f.get(1, [0])[0]
    val = f.get(2, [b""])[0]
    return key, val


def find_xplane_files(trace_dir):
    """All xplane.pb files under a jax.profiler trace directory."""
    return sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.xplane.pb")))


def parse_xspace(path):
    """xplane.pb -> list of planes:
    {"name": str, "lines": [{"name": str, "timestamp_ns": int,
    "events": [(meta_name, duration_ps, offset_ps)]}]}."""
    with open(path, "rb") as f:
        space = parse_fields(f.read())
    planes = []
    for praw in space.get(1, []):
        pf = parse_fields(praw)
        name = pf.get(2, [b""])[0].decode("utf-8", "replace")
        metas = {}
        for mraw in pf.get(4, []):
            k, v = _decode_map_entry(mraw)
            mf = parse_fields(v)
            metas[k] = mf.get(2, [b""])[0].decode("utf-8", "replace")
            # display_name (3) is the prettier name when present
            disp = mf.get(3, [b""])[0]
            if disp:
                metas[k] = disp.decode("utf-8", "replace")
        lines = []
        for lraw in pf.get(3, []):
            lf = parse_fields(lraw)
            lname = lf.get(2, [b""])[0].decode("utf-8", "replace")
            ts_ns = lf.get(3, [0])[0]
            events = []
            for eraw in lf.get(4, []):
                ef = parse_fields(eraw)
                mid = ef.get(1, [0])[0]
                off = ef.get(2, [0])[0]
                dur = ef.get(3, [0])[0]
                events.append((metas.get(mid, str(mid)), dur, off))
            lines.append({"name": lname, "timestamp_ns": ts_ns,
                          "events": events})
        planes.append({"name": name, "lines": lines})
    return planes


def op_breakdown(trace_dir, device_substr="TPU", line_substr=None):
    """Aggregate device-plane op durations across a trace directory.

    Returns [(op_name, total_ms, count)] sorted by total time descending.
    `device_substr` picks the device planes ("TPU", "GPU", or "" for
    CPU-only traces where XLA ops land on host-thread planes).
    `line_substr` picks activity lines within a plane; the default (None)
    uses the serialized "XLA Ops" line when the plane has one — summing
    every line would double-count, since "Steps" / "XLA Modules" /
    "Async XLA Ops" events span the same wall time — and otherwise
    falls back to all lines (CPU traces have per-thread lines instead).
    """
    totals, counts = {}, {}
    for path in find_xplane_files(trace_dir):
        for plane in parse_xspace(path):
            pname = plane["name"]
            if device_substr.lower() not in pname.lower():
                continue
            lines = plane["lines"]
            if line_substr is not None:
                lines = [l for l in lines if line_substr in l["name"]]
            elif any(l["name"] == "XLA Ops" for l in lines):
                lines = [l for l in lines if l["name"] == "XLA Ops"]
            for line in lines:
                for name, dur, _off in line["events"]:
                    totals[name] = totals.get(name, 0) + dur
                    counts[name] = counts.get(name, 0) + 1
    rows = [(n, t / 1e9, counts[n]) for n, t in totals.items()]
    rows.sort(key=lambda r: -r[1])
    return rows


def print_breakdown(trace_dir, top=25, device_substr="TPU",
                    line_substr=None, out=print):
    rows = op_breakdown(trace_dir, device_substr, line_substr)
    total = sum(r[1] for r in rows)
    out(f"device total: {total:.2f} ms across {len(rows)} distinct ops")
    for name, ms, n in rows[:top]:
        out(f"{ms:9.3f} ms  x{n:<5d} {name[:90]}")
    return rows


def to_chrome_trace(trace_dir, out_path, max_events=200000):
    """Convert a jax.profiler trace directory into Chrome trace-event JSON
    (open in chrome://tracing or ui.perfetto.dev — no TensorBoard needed;
    ≡ the timeline view role of the reference's UI training dashboard).

    One pid per XPlane, one tid per XLine; complete ('X') events with
    microsecond timestamps. Returns the number of events written."""
    import json

    events = []
    pid = 0
    full = False
    for path in find_xplane_files(trace_dir):
        if full:
            break
        for plane in parse_xspace(path):
            if full:
                break
            pid += 1
            events.append({"ph": "M", "pid": pid, "name": "process_name",
                           "args": {"name": plane["name"]}})
            for tid, line in enumerate(plane["lines"], 1):
                if full:
                    break
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": line["name"]}})
                base_us = line["timestamp_ns"] / 1e3
                for name, dur, off in line["events"]:
                    if len(events) >= max_events:
                        full = True
                        break
                    events.append({
                        "ph": "X", "pid": pid, "tid": tid,
                        "name": name.split(" = ")[0].lstrip("%"),
                        "ts": base_us + off / 1e6,   # ps -> us
                        "dur": max(dur / 1e6, 0.001),
                    })
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)
