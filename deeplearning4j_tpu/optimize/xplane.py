"""XPlane (jax.profiler / XLA device trace) reader.

≡ the reference's SystemInfo/profiling analysis surface (deeplearning4j-core
:: util.ModelSerializer-adjacent perf tooling; nd4j OpExecutioner profiling
mode): turns the xplane.pb protobuf written by `jax.profiler.trace` /
ProfilerListener into per-op time tables, with no tensorboard/tensorflow
dependency — the wire decoding rides the same minimal protobuf codec as the
TF frozen-graph importer (autodiff/tfproto.py).

Field numbers follow tensorflow/tsl/profiler/protobuf/xplane.proto:
  XSpace.planes = 1
  XPlane: id=1, name=2, lines=3, event_metadata=4 (map), stat_metadata=5
  XLine:  id=1, name=2, timestamp_ns=3, events=4
  XEvent: metadata_id=1, offset_ps=2, duration_ps=3, stats=4
  XEventMetadata: id=1, name=2, display_name=3, stats=5
  XStat:  metadata_id=1, double=2, uint64=3, int64=4, str=5, bytes=6, ref=7
  XStatMetadata: id=1, name=2

XStats carry XLA's per-op cost-analysis metrics ("bytes accessed",
"flops", "memory_bandwidth", occupancy...) — memory_breakdown() turns
them into the per-op bytes table the round-3 HBM-bound analysis needed.

Usage:
  rows = op_breakdown("/tmp/trace")        # aggregated per-op-name
  for name, ms, n in rows[:20]: print(f"{ms:8.2f} ms  x{n:<4d} {name}")
"""
from __future__ import annotations

import glob
import os
import struct

from deeplearning4j_tpu.autodiff.tfproto import _signed, parse_fields


def _decode_stat(raw, stat_metas):
    """XStat bytes -> (name, value). The oneof: double(2)/uint64(3)/
    int64(4)/str(5)/bytes(6)/ref(7 — index into stat_metadata)."""
    f = parse_fields(raw)
    name = stat_metas.get(f.get(1, [0])[0], str(f.get(1, [0])[0]))
    if 2 in f:
        return name, struct.unpack("<d", f[2][0])[0]
    if 3 in f:
        return name, f[3][0]
    if 4 in f:
        return name, _signed(f[4][0])
    if 5 in f:
        return name, f[5][0].decode("utf-8", "replace")
    if 6 in f:
        return name, f[6][0]
    if 7 in f:
        return name, stat_metas.get(f[7][0], str(f[7][0]))
    return name, None


def _decode_map_entry(buf):
    """protobuf map<int64, Message> entry -> (key, value_bytes)."""
    f = parse_fields(buf)
    key = f.get(1, [0])[0]
    val = f.get(2, [b""])[0]
    return key, val


def find_xplane_files(trace_dir):
    """All xplane.pb files under a jax.profiler trace directory."""
    return sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.xplane.pb")))


def parse_xspace(path, with_stats=False, plane_substr=None):
    """xplane.pb -> list of planes:
    {"name": str, "lines": [{"name": str, "timestamp_ns": int,
    "events": [(meta_name, duration_ps, offset_ps)]}]}.

    with_stats=True appends a 4th element to each event tuple — a
    {stat_name: value} dict decoded from the event's XStats merged with
    its event-METADATA stats (XLA puts static cost-analysis numbers like
    "bytes accessed"/"flops" on the metadata, per-occurrence values on
    the event). plane_substr skips non-matching planes BEFORE any event
    decoding (host/thread planes dwarf the device plane in real traces)."""
    with open(path, "rb") as f:
        space = parse_fields(f.read())
    planes = []
    for praw in space.get(1, []):
        pf = parse_fields(praw)
        name = pf.get(2, [b""])[0].decode("utf-8", "replace")
        if plane_substr is not None and \
                plane_substr.lower() not in name.lower():
            continue
        stat_metas = {}
        for mraw in pf.get(5, []):
            k, v = _decode_map_entry(mraw)
            mf = parse_fields(v)
            stat_metas[k] = mf.get(2, [b""])[0].decode("utf-8", "replace")
        metas = {}
        meta_stats = {}
        for mraw in pf.get(4, []):
            k, v = _decode_map_entry(mraw)
            mf = parse_fields(v)
            metas[k] = mf.get(2, [b""])[0].decode("utf-8", "replace")
            # display_name (3) is the prettier name when present
            disp = mf.get(3, [b""])[0]
            if disp:
                metas[k] = disp.decode("utf-8", "replace")
            if with_stats and 5 in mf:
                meta_stats[k] = dict(
                    _decode_stat(s, stat_metas) for s in mf[5])
        lines = []
        for lraw in pf.get(3, []):
            lf = parse_fields(lraw)
            lname = lf.get(2, [b""])[0].decode("utf-8", "replace")
            ts_ns = lf.get(3, [0])[0]
            events = []
            for eraw in lf.get(4, []):
                ef = parse_fields(eraw)
                mid = ef.get(1, [0])[0]
                off = ef.get(2, [0])[0]
                dur = ef.get(3, [0])[0]
                if with_stats:
                    stats = dict(meta_stats.get(mid, {}))
                    for sraw in ef.get(4, []):
                        sk, sv = _decode_stat(sraw, stat_metas)
                        stats[sk] = sv
                    events.append((metas.get(mid, str(mid)), dur, off,
                                   stats))
                else:
                    events.append((metas.get(mid, str(mid)), dur, off))
            lines.append({"name": lname, "timestamp_ns": ts_ns,
                          "events": events})
        planes.append({"name": name, "lines": lines})
    return planes


def _bytes_accessed(stats):
    """Largest 'bytes'-ish cost-analysis stat on an event — XLA variously
    spells it "bytes accessed" / "bytes_accessed" / per-memory-space
    variants; the op and memory tables must agree on the heuristic."""
    b = 0
    for k, v in stats.items():
        if "bytes" in k.lower() and isinstance(v, int):
            b = max(b, v)
    return b


def memory_breakdown(trace_dir, device_substr="TPU", line_substr=None,
                     lines=None):
    """Per-op bytes-accessed table from the XStat cost-analysis metrics:
    [(op_name, total_ms, bytes_accessed, GB_per_s)] sorted by bytes
    descending. Rides the same plane/line selection as op_breakdown; ops
    with no bytes stat report 0 (fusion roots carry the stat on TPU).
    `lines` (from collect_lines) skips the parse and reuses an
    already-decoded selection."""
    totals, nbytes = {}, {}
    if lines is None:
        lines = _selected_lines(trace_dir, device_substr, line_substr,
                                with_stats=True)
    for line in lines:
        for ev in line["events"]:
            name, dur, stats = ev[0], ev[1], ev[3]
            b = _bytes_accessed(stats)
            totals[name] = totals.get(name, 0) + dur
            nbytes[name] = nbytes.get(name, 0) + b
    rows = []
    for n, b in nbytes.items():
        ms = totals[n] / 1e9
        gbps = (b / 1e9) / (ms / 1e3) if ms > 0 else 0.0
        rows.append((n, ms, b, gbps))
    rows.sort(key=lambda r: -r[2])
    return rows


def _selected_lines(trace_dir, device_substr, line_substr, with_stats):
    """Shared plane/line selection for the breakdown tables.

    `device_substr` picks the device planes ("TPU", "GPU", or "" for
    CPU-only traces where XLA ops land on host-thread planes).
    `line_substr` picks activity lines within a plane; the default (None)
    uses the serialized "XLA Ops" line when the plane has one — summing
    every line would double-count, since "Steps" / "XLA Modules" /
    "Async XLA Ops" events span the same wall time — and otherwise
    falls back to all lines (CPU traces have per-thread lines instead)."""
    for path in find_xplane_files(trace_dir):
        for plane in parse_xspace(path, with_stats=with_stats,
                                  plane_substr=device_substr or None):
            lines = plane["lines"]
            if line_substr is not None:
                lines = [l for l in lines if line_substr in l["name"]]
            elif any(l["name"] == "XLA Ops" for l in lines):
                lines = [l for l in lines if l["name"] == "XLA Ops"]
            yield from lines


def collect_lines(trace_dir, device_substr="TPU", line_substr=None):
    """Materialize one stats-bearing plane/line selection so several
    tables (op_table + memory_breakdown) can be derived from a single
    decode of the trace — closing a ProfileSession window parses each
    candidate device plane once instead of once per table."""
    return list(_selected_lines(trace_dir, device_substr, line_substr,
                                with_stats=True))


def op_breakdown(trace_dir, device_substr="TPU", line_substr=None):
    """Aggregate device-plane op durations across a trace directory.

    Returns [(op_name, total_ms, count)] sorted by total time descending;
    see _selected_lines for the plane/line selection rules."""
    totals, counts = {}, {}
    for line in _selected_lines(trace_dir, device_substr, line_substr,
                                with_stats=False):
        for name, dur, _off in line["events"]:
            totals[name] = totals.get(name, 0) + dur
            counts[name] = counts.get(name, 0) + 1
    rows = [(n, t / 1e9, counts[n]) for n, t in totals.items()]
    rows.sort(key=lambda r: -r[1])
    return rows


def _self_times(events):
    """Per-event SELF time (duration minus nested children) for one
    line's [(name, dur_ps, off_ps), ...] events.

    XLA lines nest: a fusion event spans its constituent sub-events, and
    "total time" double-counts every level. Sweep events in start order
    (ties: longer first, so parents precede their children) with a
    containment stack; each event's duration is charged against its
    nearest enclosing ancestor. Returns self-times aligned with
    `events`' order."""
    idx = sorted(range(len(events)),
                 key=lambda i: (events[i][2], -events[i][1]))
    selfs = [0] * len(events)
    stack = []   # (end_ps, original_index) of open ancestors
    child_total = {}
    for i in idx:
        _name, dur, off = events[i][0], events[i][1], events[i][2]
        while stack and stack[-1][0] <= off:
            stack.pop()
        if stack:
            parent = stack[-1][1]
            child_total[parent] = child_total.get(parent, 0) + dur
        stack.append((off + dur, i))
    for i in range(len(events)):
        selfs[i] = max(0, events[i][1] - child_total.get(i, 0))
    return selfs


#: substring -> category for ops whose stats carry no explicit category
_NAME_CATEGORIES = (
    ("fusion", "fusion"), ("convolution", "convolution"),
    ("conv", "convolution"), ("dot", "matmul"), ("gemm", "matmul"),
    ("matmul", "matmul"), ("all-reduce", "collective"),
    ("all-gather", "collective"), ("reduce-scatter", "collective"),
    ("collective", "collective"), ("copy", "copy"),
    ("transpose", "copy"), ("reshape", "copy"), ("broadcast", "copy"),
    ("reduce", "reduce"), ("scatter", "scatter"), ("gather", "gather"),
    ("sort", "sort"), ("rng", "rng"), ("infeed", "infeed"),
    ("outfeed", "outfeed"), ("custom-call", "custom-call"),
)


def _categorize(name, stats):
    cat = stats.get("category") or stats.get("equation_category")
    if isinstance(cat, str) and cat:
        return cat
    low = name.lower()
    for sub, cat in _NAME_CATEGORIES:
        if sub in low:
            return cat
    return "other"


def op_table(trace_dir, device_substr="TPU", line_substr=None,
             lines=None):
    """The full per-op cost table ProfileSession publishes: one row per
    distinct op name with

        {"name", "total_ms", "self_ms", "count", "category",
         "flops", "bytes_accessed", "pct"}

    sorted by self_ms descending (`pct` is self_ms share of the summed
    self time, which — unlike total time — adds to ~100% even with
    nested fusion events). Plane/line selection matches op_breakdown;
    `lines` (from collect_lines) skips the parse and reuses an
    already-decoded selection."""
    rows = {}
    total_self = 0
    if lines is None:
        lines = _selected_lines(trace_dir, device_substr, line_substr,
                                with_stats=True)
    for line in lines:
        events = line["events"]
        selfs = _self_times([(e[0], e[1], e[2]) for e in events])
        for ev, self_ps in zip(events, selfs):
            name, dur, stats = ev[0], ev[1], ev[3]
            r = rows.get(name)
            if r is None:
                r = rows[name] = {
                    "name": name, "total_ms": 0.0, "self_ms": 0.0,
                    "count": 0, "category": _categorize(name, stats),
                    "flops": 0, "bytes_accessed": 0}
            r["total_ms"] += dur / 1e9
            r["self_ms"] += self_ps / 1e9
            r["count"] += 1
            total_self += self_ps
            fl = stats.get("flops")
            if isinstance(fl, int) and fl > 0:
                r["flops"] += fl
            r["bytes_accessed"] += _bytes_accessed(stats)
    out = sorted(rows.values(), key=lambda r: -r["self_ms"])
    denom = total_self / 1e9
    for r in out:
        r["pct"] = 100.0 * r["self_ms"] / denom if denom > 0 else 0.0
    return out


def category_rollup(rows):
    """Aggregate an op_table by category:
    [{"category", "self_ms", "count", "flops", "pct"}], self-time
    descending."""
    cats = {}
    for r in rows:
        c = cats.setdefault(r["category"],
                            {"category": r["category"], "self_ms": 0.0,
                             "count": 0, "flops": 0})
        c["self_ms"] += r["self_ms"]
        c["count"] += r["count"]
        c["flops"] += r["flops"]
    out = sorted(cats.values(), key=lambda c: -c["self_ms"])
    total = sum(c["self_ms"] for c in out)
    for c in out:
        c["pct"] = 100.0 * c["self_ms"] / total if total > 0 else 0.0
    return out


def render_report(rows, memory_rows=None, top=25):
    """Text report over an op_table (+ optional memory_breakdown rows):
    top-K ops by self time, the category rollup, and the top memory
    movers — the `repr` surface of a ProfileSession and the payload of
    `print_profile()`."""
    lines = []
    total_self = sum(r["self_ms"] for r in rows)
    lines.append(f"device self time: {total_self:.3f} ms across "
                 f"{len(rows)} distinct ops")
    lines.append(f"{'self ms':>10}  {'total ms':>10}  {'%':>5}  "
                 f"{'count':>6}  {'category':<12} op")
    for r in rows[:top]:
        lines.append(f"{r['self_ms']:10.3f}  {r['total_ms']:10.3f}  "
                     f"{r['pct']:5.1f}  {r['count']:6d}  "
                     f"{r['category']:<12} {r['name'][:70]}")
    lines.append("")
    lines.append("by category:")
    for c in category_rollup(rows):
        gflops = c["flops"] / 1e9
        lines.append(f"{c['self_ms']:10.3f} ms  {c['pct']:5.1f}%  "
                     f"x{c['count']:<7d} {c['category']:<12}"
                     + (f"  {gflops:.2f} GFLOP" if gflops else ""))
    if memory_rows:
        lines.append("")
        lines.append("top memory movers (bytes accessed):")
        for name, ms, b, gbps in memory_rows[:top]:
            lines.append(f"{b:14,d} B  {ms:9.3f} ms  {gbps:8.1f} GB/s  "
                         f"{name[:60]}")
    return "\n".join(lines)


def print_breakdown(trace_dir, top=25, device_substr="TPU",
                    line_substr=None, out=print):
    rows = op_breakdown(trace_dir, device_substr, line_substr)
    total = sum(r[1] for r in rows)
    out(f"device total: {total:.2f} ms across {len(rows)} distinct ops")
    for name, ms, n in rows[:top]:
        out(f"{ms:9.3f} ms  x{n:<5d} {name[:90]}")
    return rows


def to_chrome_trace(trace_dir, out_path, max_events=200000):
    """Convert a jax.profiler trace directory into Chrome trace-event JSON
    (open in chrome://tracing or ui.perfetto.dev — no TensorBoard needed;
    ≡ the timeline view role of the reference's UI training dashboard).

    One pid per XPlane, one tid per XLine; complete ('X') events with
    microsecond timestamps. Returns the number of events written."""
    import json

    events = []
    pid = 0
    full = False
    for path in find_xplane_files(trace_dir):
        if full:
            break
        for plane in parse_xspace(path):
            if full:
                break
            pid += 1
            events.append({"ph": "M", "pid": pid, "name": "process_name",
                           "args": {"name": plane["name"]}})
            for tid, line in enumerate(plane["lines"], 1):
                if full:
                    break
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": line["name"]}})
                base_us = line["timestamp_ns"] / 1e3
                for name, dur, off in line["events"]:
                    if len(events) >= max_events:
                        full = True
                        break
                    events.append({
                        "ph": "X", "pid": pid, "tid": tid,
                        "name": name.split(" = ")[0].lstrip("%"),
                        "ts": base_us + off / 1e6,   # ps -> us
                        "dur": max(dur / 1e6, 0.001),
                    })
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)
