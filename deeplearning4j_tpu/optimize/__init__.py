"""Training-loop surround: listeners, early stopping
(≡ deeplearning4j-nn optimize.listeners + deeplearning4j-core earlystopping)."""
from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CheckpointListener, CollectScoresListener, EvaluativeListener,
    PerformanceListener, ProfilerListener, ScoreIterationListener,
    TimeIterationListener, TrainingListener)
from deeplearning4j_tpu.optimize.early_stopping import (  # noqa: F401
    BestScoreEpochTerminationCondition, ClassificationScoreCalculator,
    DataSetLossCalculator, EarlyStoppingConfiguration,
    EarlyStoppingGraphTrainer, EarlyStoppingParallelTrainer,
    EarlyStoppingResult, EarlyStoppingTrainer,
    InMemoryModelSaver, InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition, ROCScoreCalculator,
    ScoreImprovementEpochTerminationCondition, TerminationReason)
