"""Training listeners (≡ deeplearning4j-nn :: optimize.listeners.*:
ScoreIterationListener, PerformanceListener, TimeIterationListener,
EvaluativeListener, CheckpointListener, and the BaseTrainingListener
protocol).

Observability cross-links (three complementary layers):
- `MetricsListener` (here) — HOST-side operational metrics + span traces
  via `deeplearning4j_tpu.monitoring` (Prometheus `/metrics` on the UI
  server, Chrome-trace JSON for Perfetto);
- `ProfilerListener` (here) + `optimize/xplane.py` — DEVICE-side XLA
  per-op traces (jax.profiler / xplane.pb);
- `ui.stats.StatsListener` — LEARNING diagnostics (score, update
  ratios, activation histograms) for the training dashboard.
"""
from __future__ import annotations

import os
import time

from deeplearning4j_tpu import monitoring as _mon


class TrainingListener:
    """Protocol: networks call iterationDone each step, onEpochEnd at epoch
    boundaries (≡ BaseTrainingListener)."""

    def iterationDone(self, model, iteration, epoch):
        pass

    def onEpochEnd(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    def __init__(self, printIterations=10, log_fn=print):
        self.every = int(printIterations)
        self.log = log_fn

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.every == 0:
            self.log(f"Score at iteration {iteration} is {model.score()}")


class PerformanceListener(TrainingListener):
    """Reports examples/sec and iterations/sec (≡ PerformanceListener)."""

    def __init__(self, frequency=10, reportBatch=True, log_fn=print):
        self.every = int(frequency)
        self.reportBatch = reportBatch
        self.log = log_fn
        self._last_time = None
        self._last_iter = 0
        self.last_throughput = None

    def iterationDone(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter = now, iteration
            return
        if iteration - self._last_iter >= self.every:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            its_per_sec = iters / dt
            self.last_throughput = its_per_sec
            self.log(f"iteration {iteration}: {its_per_sec:.2f} iters/sec "
                     f"(epoch {epoch})")
            self._last_time, self._last_iter = now, iteration


class TimeIterationListener(TrainingListener):
    """ETA logging over a planned iteration count."""

    def __init__(self, total_iterations, frequency=50, log_fn=print):
        self.total = int(total_iterations)
        self.every = int(frequency)
        self.log = log_fn
        self._start = None

    def iterationDone(self, model, iteration, epoch):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration % self.every == 0:
            elapsed = time.perf_counter() - self._start
            rate = elapsed / max(1, iteration)
            remaining = rate * max(0, self.total - iteration)
            self.log(f"iteration {iteration}/{self.total}, "
                     f"ETA {remaining:.1f}s")


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out iterator (≡ EvaluativeListener)."""

    def __init__(self, iterator, frequency, evaluation=None, log_fn=print):
        self.iterator = iterator
        self.every = int(frequency)
        self.evaluation_factory = evaluation
        self.log = log_fn
        self.last_evaluation = None

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.every != 0:
            return
        with _mon.span("listener.evaluate"):
            e = model.evaluate(self.iterator)
        self.last_evaluation = e
        self.log(f"Evaluation at iteration {iteration}: "
                 f"accuracy={e.accuracy():.4f} f1={e.f1():.4f}")


class CheckpointListener(TrainingListener):
    """≡ CheckpointListener.Builder: save every N iterations/epochs, keep
    last K checkpoints."""

    def __init__(self, directory, saveEveryNIterations=None,
                 saveEveryNEpochs=None, keepLast=3, saveUpdater=True):
        self.dir = directory
        self.every_iter = saveEveryNIterations
        self.every_epoch = saveEveryNEpochs
        self.keep = int(keepLast)
        self.saveUpdater = saveUpdater
        self._saved = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        with _mon.span("listener.checkpoint"):
            ModelSerializer.writeModel(model, path, self.saveUpdater)
        self._saved.append(path)
        while len(self._saved) > self.keep:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iterationDone(self, model, iteration, epoch):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def onEpochEnd(self, model):
        if self.every_epoch and model.getEpochCount() % self.every_epoch == 0:
            self._save(model, f"epoch_{model.getEpochCount()}")

    def lastCheckpoint(self):
        return self._saved[-1] if self._saved else None


class CollectScoresListener(TrainingListener):
    def __init__(self, frequency=1):
        self.every = int(frequency)
        self.scores = []

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.every == 0:
            self.scores.append((iteration, model.score()))


class ProfilerListener(TrainingListener):
    """Profiling that produces ARTIFACTS (round-1 VERDICT: the profiler was
    a facade nothing routed through).

    The trace-window duty is SUBSUMED by
    `monitoring.profiler.ProfileSession` (this listener drives one in
    manual begin/end mode), so the same capture also yields the decoded
    per-op report — `self.report` after the window closes, identical
    shape to `monitoring.last_report()`. Prefer
    `monitoring.profile_next_steps(k)` / `POST /profile?steps=k` for new
    code; this listener remains the iterationDone-cadence surface.

    Two outputs per training run:
    - per-iteration step timings recorded into the OpExecutioner profiler
      (≡ OpProfiler: `Nd4j.getExecutioner().getProfilingStats()`), under
      the op name "train_step";
    - an XLA device trace via jax.profiler (xplane.pb under
      `<trace_dir>/plugins/profile/<run>/`, viewable in
      TensorBoard/Perfetto) covering iterations [start_iter, start_iter +
      trace_iters), plus the decoded `self.report` per-op table.

    Usage: net.setListeners(ProfilerListener(trace_dir="/tmp/trace")).
    """

    def __init__(self, trace_dir=None, start_iter=1, trace_iters=3):
        self.trace_dir = None if trace_dir is None else str(trace_dir)
        self.start_iter = int(start_iter)
        self.trace_iters = int(trace_iters)
        self.report = None
        self._session = None
        self._last_time = None
        from deeplearning4j_tpu.runtime.executioner import OpExecutioner
        self._ex = OpExecutioner.getInstance()
        self._ex.setProfilingMode(True)

    @property
    def _tracing(self):
        return self._session is not None \
            and self._session.state == "tracing"

    def _close_window(self):
        s = self._session
        if s is not None and s.state == "tracing":
            s.end()
            self.report = s.report
        self.trace_dir = None  # one trace per listener

    def iterationDone(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._last_time is not None:
            # attribute the whole iteration to the jitted train step — the
            # reference's per-op breakdown collapses under XLA fusion into
            # one fused step executable (SURVEY §1 inversion)
            self._ex.op_counts["train_step"] += 1
            self._ex.op_times["train_step"] += now - self._last_time
        self._last_time = now
        if self.trace_dir is None:
            return
        if not self._tracing and iteration >= self.start_iter:
            from deeplearning4j_tpu.monitoring.profiler import \
                ProfileSession
            self._session = ProfileSession(steps=self.trace_iters,
                                           trace_dir=self.trace_dir,
                                           keep_trace=True)
            self._session.begin()
            if self._session.state == "failed":
                # start_trace refused (e.g. a globally-armed window
                # already has jax.profiler open) — give up instead of
                # re-trying a failing start on EVERY remaining iteration
                self._session = None
                self.trace_dir = None
        elif self._tracing:
            # listener-driven sessions are never the global ACTIVE one,
            # so the trainers' step hooks skip them — count the captured
            # step here; the k-th step_end closes the window and builds
            # the report (captured_steps then reflects reality instead
            # of staying 0)
            self._session.step_end()
            if not self._tracing:
                self.report = self._session.report
                self.trace_dir = None  # one trace per listener

    def onEpochEnd(self, model):
        # re-arm the timer: inter-epoch work (eval, checkpointing) must not
        # be attributed to the next epoch's first train_step
        self._last_time = None
        if self._tracing:
            self._close_window()


class MetricsListener(TrainingListener):
    """One-line opt-in to the HOST-side monitoring subsystem:

        net.setListeners(MetricsListener())

    Constructing it calls `monitoring.enable()` (that IS the opt-in: every
    instrumented span/metric point in the trainers, parallel stack, and
    executioner goes live), bootstraps the core metric families (jit
    compile histogram, transfer counter, device memory gauges), and then
    per iteration records:

    - `dl4j.train.iterations` (counter), `dl4j.train.score` (gauge),
    - `dl4j.train.iteration_seconds` (histogram → p50/p95/p99),
    - device memory gauges every `deviceMemoryFrequency` iterations
      (`device.memory_stats()` where the backend has it).

    `tracePath` (optional) exports the accumulated span trace as
    Chrome trace-event JSON at every epoch end — load it in Perfetto /
    chrome://tracing to see nested data-iter / dispatch / listener /
    eval / checkpoint phases.

    Scrape surface: `UIServer.getInstance().start()` then
    `GET /metrics` (Prometheus text format).

    Complements (does not replace) `ProfilerListener` (DEVICE-side
    xplane trace — see optimize/xplane.py) and `ui.stats.StatsListener`
    (learning diagnostics for the dashboard).
    """

    def __init__(self, registry=None, deviceMemoryFrequency=50,
                 tracePath=None, scoreFrequency=1):
        _mon.enable()
        self.registry = registry if registry is not None \
            else _mon.get_registry()
        _mon.bootstrap_core_metrics(self.registry)
        self.deviceMemoryFrequency = max(1, int(deviceMemoryFrequency))
        #: reading score() materializes the device loss — a host-blocking
        #: sync (counted on dl4j.pipeline.syncs). scoreFrequency=N reads
        #: it every N iterations so metrics collection doesn't serialize
        #: the async pipeline it is observing (≡ ScoreIterationListener's
        #: printIterations cadence)
        self.scoreFrequency = max(1, int(scoreFrequency))
        self.trace_path = None if tracePath is None else str(tracePath)
        self._last_time = None
        self._params_version_seen = None

    def iterationDone(self, model, iteration, epoch):
        reg = self.registry
        now = time.perf_counter()
        reg.counter("dl4j.train.iterations",
                    help="training iterations observed").inc()
        if iteration % self.scoreFrequency == 0:
            score = model.score()
            if score is not None:
                reg.gauge("dl4j.train.score",
                          help="most recent training loss") \
                   .set(float(score))
        # scanned fit (stepsPerDispatch=k) fires k iterationDone calls
        # microseconds apart after ONE dispatch; time dispatch-to-dispatch
        # via _params_version (same dedup contract as StatsListener) so
        # the histogram isn't drowned in k-1 near-zero intervals
        version = getattr(model, "_params_version", None)
        params_fresh = version is None \
            or version != self._params_version_seen
        self._params_version_seen = version
        if params_fresh:
            if self._last_time is not None:
                reg.histogram("dl4j.train.iteration_seconds",
                              help="host wall time between real param "
                                   "updates").observe(now - self._last_time)
            self._last_time = now
        if iteration % self.deviceMemoryFrequency == 0:
            # memory.sample (not bare collect_device_memory): also sets
            # the dl4j.model.*_bytes footprint gauges from the live trees
            # and retains the reading for OOM forensics
            # (util/crash_reporting.py embeds the last sample)
            _mon.memory.sample(reg, model)

    def stepRecords(self, last=None):
        """Step-time attribution records from the flight recorder
        (monitoring/steps.py) — the programmatic face of GET /steps."""
        return _mon.step_recorder().records(last=last)

    def stepSummary(self):
        """Percentile roll-up of per-step phase attribution."""
        return _mon.step_recorder().summary()

    def onEpochEnd(self, model):
        # inter-epoch work (eval/checkpoint listeners) must not count as
        # an iteration interval
        self._last_time = None
        _mon.memory.sample(self.registry, model)
        if self.trace_path:
            tracer = _mon.get_tracer()
            tracer.export(self.trace_path)
            # near the event cap, start a fresh window rather than let
            # every later span drop silently: the file just written
            # preserves the old window; subsequent epoch exports rewrite
            # the path with the newer one (late-training spans matter
            # more than re-exporting early ones)
            if len(tracer.events()) >= 0.8 * tracer.max_events:
                tracer.clear()
