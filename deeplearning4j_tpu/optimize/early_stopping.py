"""Early stopping (≡ deeplearning4j-core :: earlystopping.*:
EarlyStoppingConfiguration, EarlyStoppingTrainer, termination conditions,
score calculators, model savers, EarlyStoppingResult).

The trainer drives the network's single jitted train step per batch and
evaluates the score calculator every N epochs; best-model snapshots use
net.clone(), which DEEP-COPIES parameters — the live net's jitted train
step donates its buffers, so a reference-sharing snapshot would be deleted
by the next fit() (pinned by test_best_model_survives_further_training).
"""
from __future__ import annotations

import os
import time


class TerminationReason:
    Error = "Error"
    IterationTerminationCondition = "IterationTerminationCondition"
    EpochTerminationCondition = "EpochTerminationCondition"


# ---------------------------------------------------------------- epoch
class MaxEpochsTerminationCondition:
    requires_score = False  # checked every epoch, even non-evaluation ones

    def __init__(self, max_epochs):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch, score, minimize):
        return epoch + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs with no (min-improvement) score gain."""

    def __init__(self, max_epochs_no_improvement, min_improvement=0.0):
        self.max_no_improve = int(max_epochs_no_improvement)
        self.min_improvement = float(min_improvement)
        self._best = None
        self._since = 0

    def initialize(self):
        self._best = None
        self._since = 0

    def terminate(self, epoch, score, minimize):
        if self._best is None:
            self._best = score
            return False
        improved = ((self._best - score) if minimize else (score - self._best)
                    ) > self.min_improvement
        if improved:
            self._best = score
            self._since = 0
        else:
            self._since += 1
        return self._since >= self.max_no_improve

    def __str__(self):
        return (f"ScoreImprovementEpochTerminationCondition("
                f"{self.max_no_improve}, {self.min_improvement})")


class BestScoreEpochTerminationCondition:
    """Stop as soon as the score is better than a target value."""

    def __init__(self, best_expected_score):
        self.target = float(best_expected_score)

    def terminate(self, epoch, score, minimize):
        return score < self.target if minimize else score > self.target

    def __str__(self):
        return f"BestScoreEpochTerminationCondition({self.target})"


# ------------------------------------------------------------- iteration
class MaxTimeIterationTerminationCondition:
    def __init__(self, max_time, unit="s"):
        mult = {"s": 1.0, "sec": 1.0, "seconds": 1.0, "m": 60.0, "min": 60.0,
                "minutes": 60.0, "h": 3600.0, "hours": 3600.0,
                "ms": 1e-3}[str(unit).lower()]
        self.max_seconds = float(max_time) * mult
        self._start = None

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, score):
        if self._start is None:
            self._start = time.monotonic()
        return time.monotonic() - self._start >= self.max_seconds

    def __str__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition:
    """Terminate if the per-iteration score exceeds a bound (divergence)."""

    def __init__(self, max_score):
        self.max_score = float(max_score)

    def terminate(self, score):
        return score > self.max_score

    def __str__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition:
    def terminate(self, score):
        import math
        return math.isnan(score) or math.isinf(score)

    def __str__(self):
        return "InvalidScoreIterationTerminationCondition()"


# -------------------------------------------------------- score calculators
class DataSetLossCalculator:
    """Average (or summed) loss over a validation iterator; minimized."""

    minimize_score = True

    def __init__(self, iterator, average=True):
        self.iterator = iterator
        self.average = bool(average)

    def calculateScore(self, net):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        total, n = 0.0, 0
        for ds in self.iterator:
            b = int(ds.features.shape[0]) if hasattr(ds.features, "shape") \
                else len(ds.features)
            total += net.score(ds) * b
            n += b
        return total / n if (self.average and n) else total


class ClassificationScoreCalculator:
    """Maximize a classification metric on a validation iterator
    (≡ org.deeplearning4j.earlystopping.scorecalc.ClassificationScoreCalculator).
    metric: 'accuracy' | 'f1' | 'precision' | 'recall'."""

    minimize_score = False

    def __init__(self, metric, iterator):
        self.metric = str(metric).lower()
        self.iterator = iterator

    def calculateScore(self, net):
        e = net.evaluate(self.iterator)
        return {"accuracy": e.accuracy, "f1": e.f1, "precision": e.precision,
                "recall": e.recall}[self.metric]()


class ROCScoreCalculator:
    minimize_score = False

    def __init__(self, iterator):
        self.iterator = iterator

    def calculateScore(self, net):
        return net.evaluateROC(self.iterator).calculateAUC()


# -------------------------------------------------------------- model savers
class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def saveBestModel(self, net, score):
        self._best = (net.clone(), score)

    def saveLatestModel(self, net, score):
        self._latest = (net.clone(), score)

    def getBestModel(self):
        return self._best[0] if self._best else None

    def getLatestModel(self):
        return self._latest[0] if self._latest else None


class LocalFileModelSaver:
    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def saveBestModel(self, net, score):
        net.save(os.path.join(self.directory, "bestModel.zip"))

    def saveLatestModel(self, net, score):
        net.save(os.path.join(self.directory, "latestModel.zip"))

    def getBestModel(self):
        return self._load("bestModel.zip")

    def getLatestModel(self):
        return self._load("latestModel.zip")

    def _load(self, fname):
        path = os.path.join(self.directory, fname)
        if not os.path.exists(path):
            return None
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        return ModelSerializer.restoreModel(path)


# ------------------------------------------------------------- configuration
class EarlyStoppingConfiguration:
    def __init__(self, epoch_conditions, iteration_conditions,
                 score_calculator, model_saver, evaluate_every_n_epochs=1,
                 save_last_model=False):
        self.epoch_conditions = list(epoch_conditions)
        self.iteration_conditions = list(iteration_conditions)
        self.score_calculator = score_calculator
        self.model_saver = model_saver or InMemoryModelSaver()
        self.evaluate_every_n_epochs = int(evaluate_every_n_epochs)
        self.save_last_model = bool(save_last_model)

    class Builder:
        def __init__(self):
            self._epoch = []
            self._iter = []
            self._calc = None
            self._saver = None
            self._every_n = 1
            self._save_last = False

        def epochTerminationConditions(self, *conds):
            if len(conds) == 1 and isinstance(conds[0], (list, tuple)):
                conds = conds[0]
            self._epoch.extend(conds)
            return self

        def iterationTerminationConditions(self, *conds):
            if len(conds) == 1 and isinstance(conds[0], (list, tuple)):
                conds = conds[0]
            self._iter.extend(conds)
            return self

        def scoreCalculator(self, calc):
            self._calc = calc
            return self

        def modelSaver(self, saver):
            self._saver = saver
            return self

        def evaluateEveryNEpochs(self, n):
            self._every_n = int(n)
            return self

        def saveLastModel(self, flag=True):
            self._save_last = bool(flag)
            return self

        def build(self):
            if not self._epoch and not self._iter:
                raise ValueError(
                    "Early stopping needs at least one termination condition "
                    "(epochTerminationConditions / "
                    "iterationTerminationConditions)")
            return EarlyStoppingConfiguration(
                self._epoch, self._iter, self._calc, self._saver,
                self._every_n, self._save_last)


class EarlyStoppingResult:
    def __init__(self, termination_reason, termination_details,
                 score_vs_epoch, best_model_epoch, best_model_score,
                 total_epochs, best_model):
        self.terminationReason = termination_reason
        self.terminationDetails = termination_details
        self.scoreVsEpoch = score_vs_epoch
        self.bestModelEpoch = best_model_epoch
        self.bestModelScore = best_model_score
        self.totalEpochs = total_epochs
        self.bestModel = best_model

    def getTerminationReason(self):
        return self.terminationReason

    def getBestModelEpoch(self):
        return self.bestModelEpoch

    def getBestModelScore(self):
        return self.bestModelScore

    def getTotalEpochs(self):
        return self.totalEpochs

    def getBestModel(self):
        return self.bestModel

    def getScoreVsEpoch(self):
        return self.scoreVsEpoch

    def __str__(self):
        return (f"EarlyStoppingResult(reason={self.terminationReason}, "
                f"details={self.terminationDetails}, "
                f"bestEpoch={self.bestModelEpoch}, "
                f"bestScore={self.bestModelScore}, "
                f"totalEpochs={self.totalEpochs})")


class EarlyStoppingTrainer:
    """Drives fit + periodic scoring until a condition fires
    (≡ earlystopping.trainer.EarlyStoppingTrainer; the Graph variant is the
    same class — both network types share the fit/score surface)."""

    def __init__(self, config, network, train_iterator):
        self.config = config
        self.net = network
        self.train_iterator = train_iterator

    def fit(self):
        cfg = self.config
        for c in cfg.epoch_conditions + cfg.iteration_conditions:
            if hasattr(c, "initialize"):
                c.initialize()

        minimize = (cfg.score_calculator.minimize_score
                    if cfg.score_calculator else True)
        score_vs_epoch = {}
        best_score, best_epoch = None, -1
        epoch = 0
        reason, details = None, None

        while True:
            if hasattr(self.train_iterator, "reset"):
                self.train_iterator.reset()
            for ds in self.train_iterator:
                self.net.fit(ds)
                it_score = self.net.score()
                for c in cfg.iteration_conditions:
                    if c.terminate(it_score):
                        reason = TerminationReason.IterationTerminationCondition
                        details = str(c)
                        break
                if reason:
                    break
            if hasattr(self.net, "_epoch"):
                self.net._epoch += 1
            if reason:
                # keep the "latest" snapshot honest even on mid-epoch
                # iteration-condition termination
                if cfg.save_last_model:
                    cfg.model_saver.saveLatestModel(self.net, self.net.score())
                break

            # score only on evaluation epochs — mixing the training loss
            # into a maximized metric's best-tracking would corrupt it
            is_eval_epoch = (cfg.score_calculator is None
                             or epoch % cfg.evaluate_every_n_epochs == 0)
            if is_eval_epoch:
                if cfg.score_calculator:
                    score = float(
                        cfg.score_calculator.calculateScore(self.net))
                else:
                    score = self.net.score()
                score_vs_epoch[epoch] = score
                improved = (best_score is None
                            or (score < best_score if minimize
                                else score > best_score))
                if improved:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.saveBestModel(self.net, score)
            # "latest" means every epoch, not every evaluation epoch
            if cfg.save_last_model:
                cfg.model_saver.saveLatestModel(
                    self.net, score_vs_epoch.get(epoch, self.net.score()))

            # score-dependent conditions fire only on evaluation epochs;
            # score-free ones (MaxEpochs) are checked every epoch so they
            # can't overshoot when evaluateEveryNEpochs > 1
            for c in cfg.epoch_conditions:
                if not is_eval_epoch and getattr(c, "requires_score", True):
                    continue
                if c.terminate(epoch, best_score if not is_eval_epoch
                               else score, minimize):
                    reason = TerminationReason.EpochTerminationCondition
                    details = str(c)
                    break
            epoch += 1
            if reason:
                break

        best = cfg.model_saver.getBestModel() or self.net
        return EarlyStoppingResult(
            reason or TerminationReason.Error, details, score_vs_epoch,
            best_epoch, best_score, epoch, best)


# Graph variant shares the implementation (same fit/score surface)
EarlyStoppingGraphTrainer = EarlyStoppingTrainer


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """≡ deeplearning4j-parallel-wrapper ::
    parallelism.EarlyStoppingParallelTrainer — early stopping over
    data-parallel training. The reference coordinates worker threads;
    here each epoch's fit runs the SPMD dp step via ParallelWrapper
    (optionally with ZeRO-1 state sharding) and the scoring/termination
    loop is inherited unchanged."""

    def __init__(self, config, network, train_iterator, workers=None,
                 shard_optimizer_state=False):
        super().__init__(config, network, train_iterator)
        if network._params is None:
            network.init()
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        self._pw = ParallelWrapper(
            network, workers=workers,
            shard_optimizer_state=shard_optimizer_state)
        self._pw._shard_model()
        # route per-DataSet fits through the SAME dp inner loop as
        # ParallelWrapper.fit (masks, padding, listeners included); every
        # other attribute access — reads AND writes (epoch counters!) —
        # passes straight through to the real network
        self.net = _DpFitProxy(self._pw)


class _DpFitProxy:
    """Network stand-in whose fit(ds) is ParallelWrapper._fit_dataset;
    everything else (including attribute writes like `_epoch += 1`)
    operates on the wrapped network itself."""

    def __init__(self, pw):
        object.__setattr__(self, "_pw", pw)

    def fit(self, ds):
        return self._pw._fit_dataset(ds)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_pw").model, name)

    def __setattr__(self, name, value):
        setattr(object.__getattribute__(self, "_pw").model, name, value)
