"""Spark training surface, local mode (≡ dl4j-spark ::
SparkDl4jMultiLayer / SparkComputationGraph, ParameterAveraging- and
SharedTrainingMaster, over an RDD of DataSet).

The reference distributes via a Spark cluster: workers pull RDD
partitions, compute on their GPU, and synchronize through the
TrainingMaster (periodic parameter averaging, or Aeron threshold-encoded
gradient sharing). The TPU-native inversion keeps the API but maps the
execution onto the device mesh: an "RDD" is a partitioned local dataset,
"workers" are dp shards of ONE jitted SPMD program, and both training
masters lower to the synchronous all-reduce step (every-step sync is the
averagingFrequency=1 / threshold=0 case of the reference, with none of
its staleness — ICI makes the sync effectively free, which is why the
reference's asynchrony workarounds are not ported). True multi-HOST
scale-out uses parallel.multihost (jax.distributed over DCN) underneath
the same classes; genuine Spark-cluster RDD ingestion remains N/A by
design (no JVM in this stack — SURVEY §2).

Usage parity:
    sc = JavaSparkContext(SparkConf().setMaster("local[*]"))
    rdd = sc.parallelize(list_of_datasets, numSlices=8)
    tm = (ParameterAveragingTrainingMaster.Builder(32)
          .averagingFrequency(5).batchSizePerWorker(32).build())
    sparkNet = SparkDl4jMultiLayer(sc, conf, tm)
    sparkNet.fit(rdd); net = sparkNet.getNetwork()
"""
from __future__ import annotations

import numpy as np


class SparkConf:
    """≡ org.apache.spark.SparkConf (local-mode shim)."""

    def __init__(self):
        self._conf = {}

    def setMaster(self, master):
        self._conf["master"] = master
        return self

    def setAppName(self, name):
        self._conf["appName"] = name
        return self

    def set(self, key, value):
        self._conf[key] = value
        return self

    def get(self, key, default=None):
        return self._conf.get(key, default)


class RDD:
    """Minimal RDD: a partitioned local collection (enough surface for
    the reference's training examples: parallelize → map/filter →
    fit/collect)."""

    def __init__(self, partitions):
        self._parts = [list(p) for p in partitions]

    def collect(self):
        return [x for p in self._parts for x in p]

    def count(self):
        return sum(len(p) for p in self._parts)

    def getNumPartitions(self):
        return len(self._parts)

    def map(self, fn):
        return RDD([[fn(x) for x in p] for p in self._parts])

    def filter(self, fn):
        return RDD([[x for x in p if fn(x)] for p in self._parts])

    def union(self, other):
        return RDD(self._parts + other._parts)

    @staticmethod
    def _chunk(items, n):
        """CONTIGUOUS chunks — parallelize/collect must preserve element
        order, as Spark's local mode does."""
        n = max(1, int(n))
        size = -(-len(items) // n) if items else 1
        return [items[i * size:(i + 1) * size] for i in range(n)]

    def repartition(self, n):
        return RDD(self._chunk(self.collect(), n))

    def foreachPartition(self, fn):
        for p in self._parts:
            fn(iter(p))


class JavaSparkContext:
    """≡ JavaSparkContext — local-mode: partitioned in-memory RDDs."""

    def __init__(self, conf=None):
        self.conf = conf or SparkConf().setMaster("local[*]")

    def parallelize(self, data, numSlices=None):
        data = list(data)
        n = max(1, int(numSlices) if numSlices else min(8, len(data) or 1))
        return RDD(RDD._chunk(data, n))

    def stop(self):
        pass


SparkContext = JavaSparkContext


class _TrainingMaster:
    #: accepted config keys — a typo'd builder method must FAIL at
    #: build(), like the reference's typed Java builders fail to compile
    _KNOWN = {"batchSizePerWorker", "averagingFrequency",
              "workerPrefetchNumBatches", "workers",
              "rddDataSetNumExamples", "collectTrainingStats",
              "rddTrainingApproach", "storageLevel", "repartionData",
              "repartitionData", "repartitionStrategy"}

    def __init__(self, **kw):
        unknown = set(kw) - self._KNOWN
        if unknown:
            raise ValueError(
                f"{type(self).__name__}: unknown option(s) "
                f"{sorted(unknown)} — known: {sorted(self._KNOWN)}")
        # reference default batch per worker is 16; batchSizePerWorker is
        # a SETTER in dl4j-spark, never a Builder positional arg
        self.batchSizePerWorker = int(kw.get("batchSizePerWorker", 16))
        self.averagingFrequency = int(kw.get("averagingFrequency", 1))
        self.workerPrefetchNumBatches = int(
            kw.get("workerPrefetchNumBatches", 2))
        self.workers = kw.get("workers")
        self.rddDataSetNumExamples = int(
            kw.get("rddDataSetNumExamples", 1))
        self.collectTrainingStats = bool(kw.get("collectTrainingStats",
                                                False))

    class _Builder:
        _cls = None

        def __init__(self, *args):
            # reference Builder positional forms:
            #   Builder(rddDataSetNumExamples)
            #   Builder(numWorkers, rddDataSetNumExamples)
            self._kw = {}
            if len(args) == 1:
                self._kw["rddDataSetNumExamples"] = int(args[0])
            elif len(args) == 2:
                self._kw["workers"] = int(args[0])
                self._kw["rddDataSetNumExamples"] = int(args[1])
            elif args:
                raise TypeError(
                    "Builder takes (rddDataSetNumExamples) or "
                    "(numWorkers, rddDataSetNumExamples)")

        def __getattr__(self, name):
            if name.startswith("_"):
                raise AttributeError(name)

            def setter(v):
                self._kw[name] = v
                return self

            return setter

        def build(self):
            return self._cls(**self._kw)


class ParameterAveragingTrainingMaster(_TrainingMaster):
    """≡ dl4j-spark :: ParameterAveragingTrainingMaster. On the mesh the
    sync step IS the averagingFrequency=1 semantics; the configured
    frequency is recorded (and honored by ParallelWrapper's reporting)
    rather than re-introducing staleness."""

    class Builder(_TrainingMaster._Builder):
        pass


ParameterAveragingTrainingMaster.Builder._cls = \
    ParameterAveragingTrainingMaster


class SharedTrainingMaster(_TrainingMaster):
    """≡ dl4j-spark-parameterserver :: SharedTrainingMaster (threshold-
    encoded gradient sharing). Thresholds are recorded; the mesh step
    all-reduces exact gradients every step — the threshold=0 limit."""

    _KNOWN = _TrainingMaster._KNOWN | {"updatesThreshold",
                                       "thresholdAlgorithm",
                                       "batchSize"}

    def __init__(self, **kw):
        super().__init__(**{k: v for k, v in kw.items()
                            if k in _TrainingMaster._KNOWN})
        unknown = set(kw) - self._KNOWN
        if unknown:
            raise ValueError(
                f"SharedTrainingMaster: unknown option(s) "
                f"{sorted(unknown)}")
        self.updatesThreshold = float(kw.get("updatesThreshold", 1e-3))
        self.rddTrainingApproach = kw.get("rddTrainingApproach", "Export")

    class Builder(_TrainingMaster._Builder):
        pass


SharedTrainingMaster.Builder._cls = SharedTrainingMaster


class SparkDl4jMultiLayer:
    """≡ dl4j-spark :: SparkDl4jMultiLayer — fit a MultiLayerNetwork from
    an RDD<DataSet> via the dp mesh (ParallelWrapper underneath)."""

    _is_graph = False

    def __init__(self, sc, conf_or_net, trainingMaster):
        self.sc = sc
        self.tm = trainingMaster
        net = conf_or_net
        if not hasattr(net, "fit"):        # a configuration: build it
            if self._is_graph:
                from deeplearning4j_tpu.nn.graph import ComputationGraph
                net = ComputationGraph(net)
            else:
                from deeplearning4j_tpu.nn.multilayer import \
                    MultiLayerNetwork
                net = MultiLayerNetwork(net)
        if net._params is None:
            net.init()
        self.net = net

    def getNetwork(self):
        return self.net

    def _iterator(self, rdd):
        from deeplearning4j_tpu.datasets.iterators import \
            ListDataSetIterator
        data = rdd.collect() if isinstance(rdd, RDD) else list(rdd)
        if not data:
            raise ValueError("fit(): empty RDD")
        return ListDataSetIterator(data, self.tm.batchSizePerWorker)

    def _wrapper(self):
        """Built once: mesh construction + param replication must not be
        paid per fit() call (the epoch-loop idiom calls fit repeatedly)."""
        pw = getattr(self, "_pw", None)
        if pw is None:
            import jax

            from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
            n = self.tm.workers or len(jax.devices())
            pw = self._pw = (
                ParallelWrapper.Builder(self.net)
                .workers(n)
                .prefetchBuffer(self.tm.workerPrefetchNumBatches)
                .averagingFrequency(self.tm.averagingFrequency)
                .build())
        return pw

    def fit(self, rdd, epochs=1):
        self._wrapper().fit(self._iterator(rdd), epochs=epochs)
        return self.net

    def evaluate(self, rdd, evaluation=None):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = evaluation or Evaluation()
        for ds in self._iterator(rdd):
            preds = self.net.output(ds.features)
            mask = getattr(ds, "labelsMask", None)
            ev.eval(ds.labels, np.asarray(preds.numpy()), mask)
        return ev

    def getScore(self):
        return float(self.net.score())


class SparkComputationGraph(SparkDl4jMultiLayer):
    """≡ dl4j-spark :: SparkComputationGraph — the graph twin."""

    _is_graph = True


__all__ = ["SparkConf", "SparkContext", "JavaSparkContext", "RDD",
           "ParameterAveragingTrainingMaster", "SharedTrainingMaster",
           "SparkDl4jMultiLayer", "SparkComputationGraph"]
