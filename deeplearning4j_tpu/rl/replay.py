"""Experience replay (≡ rl4j-core :: learning.sync.ExpReplay /
Transition): fixed-capacity ring buffer with uniform minibatch sampling
into fixed-shape numpy batches ready for the jitted Q-update."""
from __future__ import annotations

import numpy as np


class Transition:
    __slots__ = ("obs", "action", "reward", "next_obs", "done")

    def __init__(self, obs, action, reward, next_obs, done):
        self.obs = obs
        self.action = action
        self.reward = reward
        self.next_obs = next_obs
        self.done = done


class ExpReplay:
    def __init__(self, max_size=150000, batch_size=32, seed=0):
        self.max_size = int(max_size)
        self.batch_size = int(batch_size)
        self._rng = np.random.default_rng(seed)
        self._buf = []
        self._pos = 0

    def store(self, transition):
        if len(self._buf) < self.max_size:
            self._buf.append(transition)
        else:
            self._buf[self._pos] = transition
        self._pos = (self._pos + 1) % self.max_size

    def __len__(self):
        return len(self._buf)

    def getBatch(self, batch_size=None):
        """Uniform sample → (obs, actions, rewards, next_obs, dones)."""
        bs = batch_size or self.batch_size
        idx = self._rng.integers(len(self._buf), size=bs)
        trans = [self._buf[i] for i in idx]
        return (np.stack([t.obs for t in trans]).astype(np.float32),
                np.asarray([t.action for t in trans], np.int32),
                np.asarray([t.reward for t in trans], np.float32),
                np.stack([t.next_obs for t in trans]).astype(np.float32),
                np.asarray([t.done for t in trans], np.float32))
