"""Advantage actor-critic + n-step Q (≡ rl4j-core :: learning.async.
a3c.discrete.A3CDiscreteDense, nstep.discrete.AsyncNStepQLearningDiscreteDense,
and the REINFORCE-style policy-gradient family).

Architectural inversion: the reference decorrelates experience with MANY
async CPU threads each running its own env + a shared lock-free global
net (Hogwild-style). On TPU the same decorrelation comes from BATCHED
environments: N env instances step host-side, and one jitted
actor-critic update consumes the whole (N, T) rollout — n-step advantage
returns computed in the XLA graph, policy + value + entropy losses fused
into a single executable. Same estimator, hardware-shaped execution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _mlp_init(key, sizes):
    params = []
    for n_in, n_out in zip(sizes[:-1], sizes[1:]):
        key, k = jax.random.split(key)
        w = jax.random.normal(k, (n_in, n_out)) * jnp.sqrt(2.0 / n_in)
        params.append({"w": w, "b": jnp.zeros((n_out,))})
    return params


def _mlp_apply(params, x):
    for i, lyr in enumerate(params):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


class A3CConfiguration:
    """≡ A3CLearningConfiguration (numThread → numEnvs)."""

    def __init__(self, seed=123, maxEpochStep=200, maxStep=20000,
                 numEnvs=8, nstep=5, gamma=0.99, learningRate=7e-4,
                 entropyCoef=0.01, valueCoef=0.5, hiddenNodes=64,
                 numLayers=2):
        self.seed = seed
        self.maxEpochStep = maxEpochStep
        self.maxStep = maxStep
        self.numEnvs = numEnvs
        self.nstep = nstep
        self.gamma = gamma
        self.learningRate = learningRate
        self.entropyCoef = entropyCoef
        self.valueCoef = valueCoef
        self.hiddenNodes = hiddenNodes
        self.numLayers = numLayers


class A3CDiscreteDense:
    """Batched-env A2C with the A3CDiscreteDense training surface."""

    def __init__(self, mdp_factory, conf=None):
        self.conf = conf or A3CConfiguration()
        c = self.conf
        self.envs = [mdp_factory() for _ in range(c.numEnvs)]
        obs_dim = int(np.prod(self.envs[0].getObservationSpace().shape))
        self.num_actions = self.envs[0].getActionSpace().getSize()
        key = jax.random.PRNGKey(c.seed)
        k1, k2, k3 = jax.random.split(key, 3)
        body_sizes = [obs_dim] + [c.hiddenNodes] * c.numLayers
        self.params = {
            "body": _mlp_init(k1, body_sizes),
            "pi": _mlp_init(k2, [c.hiddenNodes, self.num_actions]),
            "v": _mlp_init(k3, [c.hiddenNodes, 1]),
        }
        self._init_trainer_state()

    def _init_trainer_state(self):
        """Optimizer + rollout bookkeeping — ONE definition shared by the
        dense and conv trainers (self.conf and self.params must be set)."""
        c = self.conf
        self.tx = optax.rmsprop(c.learningRate, decay=0.99, eps=1e-5)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(c.seed)
        self.step_count = 0
        self.episode_rewards = []
        self._ep_acc = np.zeros(c.numEnvs)
        self._update = self._build_update()

    # -- jitted policy/value ---------------------------------------------
    def _features(self, params, obs):
        """Shared feature extractor — the ONLY thing subclasses override
        (dense: body MLP; conv: conv torso)."""
        return _mlp_apply(params["body"], obs)

    @functools.partial(jax.jit, static_argnums=0)
    def _logits_values(self, params, obs):
        h = self._features(params, obs)
        return _mlp_apply(params["pi"], h), _mlp_apply(params["v"], h)[..., 0]

    def _build_update(self):
        c = self.conf
        tx = self.tx
        features = self._features

        @jax.jit
        def update(params, opt_state, obs, actions, returns):
            """obs: (N*T, ...); returns: n-step bootstrapped targets."""

            def loss_fn(p):
                h = features(p, obs)
                logits = _mlp_apply(p["pi"], h)
                values = _mlp_apply(p["v"], h)[..., 0]
                logp = jax.nn.log_softmax(logits)
                probs = jax.nn.softmax(logits)
                adv = returns - values
                chosen = jnp.take_along_axis(
                    logp, actions[:, None], axis=-1)[:, 0]
                pg_loss = -(chosen * jax.lax.stop_gradient(adv)).mean()
                v_loss = (adv ** 2).mean()
                entropy = -(probs * logp).sum(-1).mean()
                return (pg_loss + c.valueCoef * v_loss
                        - c.entropyCoef * entropy)

            grads = jax.grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        return update

    def _act(self, obs_batch):
        logits, values = self._logits_values(self.params,
                                             jnp.asarray(obs_batch))
        probs = np.asarray(jax.nn.softmax(logits))
        actions = np.array([self._rng.choice(self.num_actions, p=p / p.sum())
                            for p in probs], np.int32)
        return actions, np.asarray(values)

    def train(self):
        c = self.conf
        obs = np.stack([e.reset() for e in self.envs]).astype(np.float32)
        while self.step_count < c.maxStep:
            roll_obs, roll_act, roll_rew, roll_done = [], [], [], []
            for _ in range(c.nstep):
                actions, _ = self._act(obs)
                next_obs = np.empty_like(obs)
                rewards = np.zeros(c.numEnvs, np.float32)
                dones = np.zeros(c.numEnvs, np.float32)
                for i, env in enumerate(self.envs):
                    o, r, d, _ = env.step(int(actions[i]))
                    self._ep_acc[i] += r
                    if d:
                        self.episode_rewards.append(self._ep_acc[i])
                        self._ep_acc[i] = 0.0
                        o = env.reset()
                    next_obs[i], rewards[i], dones[i] = o, r, float(d)
                roll_obs.append(obs.copy())
                roll_act.append(actions)
                roll_rew.append(rewards)
                roll_done.append(dones)
                obs = next_obs
                self.step_count += c.numEnvs
            # n-step bootstrapped returns (host; tiny T loop)
            _, boot = self._act(obs)
            returns = np.zeros((c.nstep, c.numEnvs), np.float32)
            running = boot
            for t in reversed(range(c.nstep)):
                running = roll_rew[t] + c.gamma * running * (1 - roll_done[t])
                returns[t] = running
            self.params, self.opt_state = self._update(
                self.params, self.opt_state,
                jnp.asarray(np.concatenate(roll_obs)),
                jnp.asarray(np.concatenate(roll_act)),
                jnp.asarray(returns.reshape(-1)))
        return self.episode_rewards

    # -- play surface -----------------------------------------------------
    def nextAction(self, obs):
        logits, _ = self._logits_values(self.params,
                                        jnp.asarray(obs[None]))
        return int(np.argmax(np.asarray(logits)[0]))

    def play(self, mdp, max_steps=10000):
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.nextAction(obs))
            total += r
            if done:
                break
        return total


class AsyncNStepQLearningDiscreteDense(A3CDiscreteDense):
    """≡ AsyncNStepQLearningDiscreteDense — same batched-env rollout
    machinery but a pure Q head trained on n-step returns (no policy
    head; ε-greedy behaviour policy)."""

    def __init__(self, mdp_factory, conf=None, minEpsilon=0.1,
                 epsilonNbStep=5000):
        super().__init__(mdp_factory, conf)
        self.minEpsilon = minEpsilon
        self.epsilonNbStep = epsilonNbStep
        # reuse pi head as the Q head; drop the value head from updates
        tx = self.tx
        c = self.conf

        @jax.jit
        def update(params, opt_state, obs, actions, returns):
            def loss_fn(p):
                q = _mlp_apply(p["pi"], _mlp_apply(p["body"], obs))
                chosen = jnp.take_along_axis(
                    q, actions[:, None], axis=-1)[:, 0]
                return ((returns - chosen) ** 2).mean()

            grads = jax.grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._update = update

    def _act(self, obs_batch):
        logits, values = self._logits_values(self.params,
                                             jnp.asarray(obs_batch))
        q = np.asarray(logits)
        frac = min(1.0, self.step_count / max(1, self.epsilonNbStep))
        eps = 1.0 + frac * (self.minEpsilon - 1.0)
        actions = q.argmax(-1).astype(np.int32)
        explore = self._rng.random(len(actions)) < eps
        actions[explore] = self._rng.integers(
            self.num_actions, size=int(explore.sum()))
        return actions, q.max(-1)


class _PixelEnvAdapter:
    """Wraps a pixel MDP with the HistoryProcessor pipeline + frame-skip
    action repeat so the batched A2C rollout sees processed (H, W, hist)
    stacks — the conv twin of the dense envs."""

    def __init__(self, mdp, hp_conf=None):
        from deeplearning4j_tpu.rl.conv import (HistoryProcessor,
                                                HistoryProcessorConfiguration)
        self.mdp = mdp
        self.hp = HistoryProcessor(hp_conf or
                                   HistoryProcessorConfiguration())
        self.skip = max(1, self.hp.conf.skipFrame)

    def getActionSpace(self):
        return self.mdp.getActionSpace()

    def getObservationSpace(self):
        class _Space:
            shape = (self.hp.conf.rescaledHeight,
                     self.hp.conf.rescaledWidth,
                     self.hp.conf.historyLength)
        return _Space()

    def reset(self):
        frame = self.mdp.reset()
        self.hp.reset()
        self.hp.record(frame)
        return self.hp.getHistory()

    def step(self, action):
        reward, done, frame = 0.0, False, None
        for _ in range(self.skip):
            frame, r, done, _ = self.mdp.step(int(action))
            reward += r
            if done:
                break
        self.hp.record(frame)
        return self.hp.getHistory(), reward, done, {}


class A3CDiscreteConv(A3CDiscreteDense):
    """≡ rl4j :: a3c.discrete.A3CDiscreteConv +
    ActorCriticFactoryCompGraphStdConv — batched-env A2C over a PIXEL
    MDP: shared conv torso (NHWC convs on the MXU) feeding policy and
    value heads, observations from the HistoryProcessor frame pipeline
    with frame-skip action repeat. Reuses the dense trainer's rollout/
    update machinery; only the network and the env adapter differ."""

    def __init__(self, mdp_factory, conf=None, hp_conf=None, net_conf=None):
        from deeplearning4j_tpu.rl.conv import DQNConvNetworkConfiguration
        self.conf = c = conf or A3CConfiguration()
        self.net_conf = nc = net_conf or DQNConvNetworkConfiguration()
        self._hp_conf = hp_conf
        self.envs = [_PixelEnvAdapter(mdp_factory(), hp_conf)
                     for _ in range(c.numEnvs)]
        h, w, ch = self.envs[0].getObservationSpace().shape
        self.num_actions = self.envs[0].getActionSpace().getSize()
        key = jax.random.PRNGKey(c.seed)
        conv_params, cin = [], ch
        oh, ow = h, w
        for f, khw, s in zip(nc.filters, nc.kernels, nc.strides):
            key, k = jax.random.split(key)
            fan_in = khw[0] * khw[1] * cin
            conv_params.append({
                "w": jax.random.normal(k, (khw[0], khw[1], cin, f))
                * jnp.sqrt(2.0 / fan_in),
                "b": jnp.zeros((f,))})
            oh = (oh - khw[0]) // s[0] + 1
            ow = (ow - khw[1]) // s[1] + 1
            cin = f
        flat = oh * ow * cin
        k1, k2, k3 = jax.random.split(key, 3)
        self.params = {
            "conv": conv_params,
            "body": _mlp_init(k1, [flat, nc.denseUnits]),
            "pi": _mlp_init(k2, [nc.denseUnits, self.num_actions]),
            "v": _mlp_init(k3, [nc.denseUnits, 1]),
        }
        self._init_trainer_state()

    def _features(self, params, obs):
        x = obs
        for lyr, s in zip(params["conv"], self.net_conf.strides):
            x = jax.lax.conv_general_dilated(
                x, lyr["w"], window_strides=tuple(s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + lyr["b"]
            x = jax.nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        return jax.nn.relu(_mlp_apply(params["body"], x))

    def play(self, mdp, max_steps=10000):
        """Greedy play on a RAW pixel MDP: frames go through the same
        HistoryProcessor pipeline the trainer used (≡ the DQN path's
        _ConvDQNPolicy)."""
        env = _PixelEnvAdapter(mdp, self._hp_conf)
        obs = env.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = env.step(self.nextAction(obs))
            total += r
            if done:
                break
        return total
