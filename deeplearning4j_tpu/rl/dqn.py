"""Deep Q-learning (≡ rl4j-core :: learning.sync.qlearning.discrete.
QLearningDiscrete / QLearningDiscreteDense, network.dqn.DQNFactoryStdDense,
policy.EpsGreedy / DQNPolicy).

The Q-network is a regular MultiLayerNetwork (MSE head) built by
DQNFactoryStdDense — exactly the reference's wiring — so each TD update
is the framework's single jitted donated train step; the target network
is a deep clone refreshed every `targetDqnUpdateFreq` steps. Double-DQN
(argmax from the online net, value from the target net) is on by default
as in the reference.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition


class QLearningConfiguration:
    """≡ QLearning.QLConfiguration (builder-style kwargs)."""

    def __init__(self, seed=123, maxEpochStep=200, maxStep=10000,
                 expRepMaxSize=150000, batchSize=32, targetDqnUpdateFreq=100,
                 updateStart=10, rewardFactor=1.0, gamma=0.99,
                 errorClamp=1.0, minEpsilon=0.1, epsilonNbStep=3000,
                 doubleDQN=True):
        self.seed = seed
        self.maxEpochStep = maxEpochStep
        self.maxStep = maxStep
        self.expRepMaxSize = expRepMaxSize
        self.batchSize = batchSize
        self.targetDqnUpdateFreq = targetDqnUpdateFreq
        self.updateStart = updateStart
        self.rewardFactor = rewardFactor
        self.gamma = gamma
        self.errorClamp = errorClamp
        self.minEpsilon = minEpsilon
        self.epsilonNbStep = epsilonNbStep
        self.doubleDQN = doubleDQN


class DQNDenseNetworkConfiguration:
    """≡ network.configuration.DQNDenseNetworkConfiguration."""

    def __init__(self, numLayers=2, numHiddenNodes=64, learningRate=1e-3,
                 l2=0.0, updater=None):
        self.numLayers = numLayers
        self.numHiddenNodes = numHiddenNodes
        self.learningRate = learningRate
        self.l2 = l2
        self.updater = updater


class DQNFactoryStdDense:
    """≡ network.dqn.DQNFactoryStdDense — builds the MLP Q-network."""

    def __init__(self, conf: DQNDenseNetworkConfiguration):
        self.conf = conf

    def buildDQN(self, obs_dim, num_actions, seed=123):
        c = self.conf
        b = (NeuralNetConfiguration.Builder()
             .seed(seed)
             .updater(c.updater or Adam(c.learningRate))
             .weightInit("xavier")
             .l2(c.l2)
             .list())
        for _ in range(c.numLayers):
            b.layer(DenseLayer(nOut=c.numHiddenNodes, activation="relu"))
        b.layer(OutputLayer(lossFunction="mse", nOut=num_actions,
                            activation="identity"))
        return MultiLayerNetwork(
            b.setInputType(InputType.feedForward(obs_dim)).build()).init()


class EpsGreedy:
    """≡ policy.EpsGreedy — linear ε annealing over epsilonNbStep."""

    def __init__(self, conf: QLearningConfiguration, rng):
        self.conf = conf
        self.rng = rng
        self.step = 0

    def epsilon(self):
        c = self.conf
        frac = min(1.0, self.step / max(1, c.epsilonNbStep))
        return 1.0 + frac * (c.minEpsilon - 1.0)

    def nextAction(self, q_values, action_space):
        self.step += 1
        if self.rng.random() < self.epsilon():
            return action_space.randomAction(self.rng)
        return int(np.argmax(q_values))


class DQNPolicy:
    """≡ policy.DQNPolicy — greedy play with a trained Q-network."""

    def __init__(self, network):
        self.network = network

    def nextAction(self, obs):
        q = np.asarray(self.network.output(obs[None]))[0]
        return int(np.argmax(q))

    def play(self, mdp, max_steps=10000):
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.nextAction(obs))
            total += r
            if done:
                break
        return total


def td_learn_batch(net, target, replay, conf):
    """One (double-)DQN TD update on a replay batch — shared by the dense
    and conv learners: bootstrap from the target net (argmax from the
    online net when doubleDQN), clamp the TD error, fit on the patched
    Q-table (the reference's QLearning.setTarget path)."""
    obs, actions, rewards, next_obs, dones = replay.getBatch()
    q_next_t = np.asarray(target.output(next_obs))
    if conf.doubleDQN:
        best = np.asarray(net.output(next_obs)).argmax(-1)
        boot = q_next_t[np.arange(len(best)), best]
    else:
        boot = q_next_t.max(-1)
    td_target = rewards * conf.rewardFactor \
        + conf.gamma * boot * (1 - dones)
    q = np.array(net.output(obs))  # copy: jax buffers are read-only
    err = td_target - q[np.arange(len(actions)), actions]
    if conf.errorClamp:
        err = np.clip(err, -conf.errorClamp, conf.errorClamp)
    q[np.arange(len(actions)), actions] += err
    net.fit(obs, q)


class QLearningDiscreteDense:
    """≡ QLearningDiscreteDense — sync DQN over an MDP with dense obs."""

    def __init__(self, mdp, net_conf, ql_conf=None):
        self.mdp = mdp
        self.conf = ql_conf or QLearningConfiguration()
        if isinstance(net_conf, DQNDenseNetworkConfiguration):
            net_conf = DQNFactoryStdDense(net_conf)
        obs_dim = int(np.prod(mdp.getObservationSpace().shape))
        self.num_actions = mdp.getActionSpace().getSize()
        self.net = net_conf.buildDQN(obs_dim, self.num_actions,
                                     self.conf.seed)
        self.target = self.net.clone()
        self._rng = np.random.default_rng(self.conf.seed)
        self.replay = ExpReplay(self.conf.expRepMaxSize,
                                self.conf.batchSize, self.conf.seed)
        self.policy = EpsGreedy(self.conf, self._rng)
        self.step_count = 0
        self.epoch_rewards = []

    def getPolicy(self):
        return DQNPolicy(self.net)

    def _learn_batch(self):
        td_learn_batch(self.net, self.target, self.replay, self.conf)

    def train(self):
        """Run until maxStep env steps; returns per-epoch reward list."""
        c = self.conf
        while self.step_count < c.maxStep:
            obs = self.mdp.reset()
            ep_reward, ep_steps = 0.0, 0
            while not self.mdp.isDone() and ep_steps < c.maxEpochStep \
                    and self.step_count < c.maxStep:
                q = np.asarray(self.net.output(obs[None]))[0]
                action = self.policy.nextAction(
                    q, self.mdp.getActionSpace())
                next_obs, reward, done, _ = self.mdp.step(action)
                self.replay.store(
                    Transition(obs, action, reward, next_obs, done))
                obs = next_obs
                ep_reward += reward
                ep_steps += 1
                self.step_count += 1
                if (self.step_count > c.updateStart
                        and len(self.replay) >= c.batchSize):
                    self._learn_batch()
                if self.step_count % c.targetDqnUpdateFreq == 0:
                    self.target.setParams(self.net.params())
            self.epoch_rewards.append(ep_reward)
        return self.epoch_rewards
