"""RL (≡ rl4j): MDPs, experience replay, sync DQN, batched-env A2C/A3C,
async n-step Q — policy surfaces mirroring rl4j's learning classes."""
from deeplearning4j_tpu.rl.mdp import (CartpoleNative, DiscreteSpace, MDP,
                                       ObservationSpace, PixelGridWorld,
                                       SimpleToy)
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.rl.dqn import (DQNDenseNetworkConfiguration,
                                       DQNFactoryStdDense, DQNPolicy,
                                       EpsGreedy, QLearningConfiguration,
                                       QLearningDiscreteDense)
from deeplearning4j_tpu.rl.a3c import (A3CConfiguration, A3CDiscreteConv,
                                       A3CDiscreteDense,
                                       AsyncNStepQLearningDiscreteDense)
from deeplearning4j_tpu.rl.conv import (DQNConvNetworkConfiguration,
                                        DQNFactoryStdConv, HistoryProcessor,
                                        HistoryProcessorConfiguration,
                                        QLearningDiscreteConv)

__all__ = [
    "CartpoleNative", "DiscreteSpace", "MDP", "ObservationSpace",
    "PixelGridWorld", "SimpleToy", "ExpReplay", "Transition",
    "DQNDenseNetworkConfiguration", "DQNFactoryStdDense", "DQNPolicy",
    "EpsGreedy", "QLearningConfiguration", "QLearningDiscreteDense",
    "A3CConfiguration", "A3CDiscreteConv", "A3CDiscreteDense",
    "AsyncNStepQLearningDiscreteDense",
    "DQNConvNetworkConfiguration", "DQNFactoryStdConv", "HistoryProcessor",
    "HistoryProcessorConfiguration", "QLearningDiscreteConv",
]
