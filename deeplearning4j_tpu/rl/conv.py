"""Pixel-input DQN (≡ rl4j-core :: learning.HistoryProcessor,
network.dqn.DQNFactoryStdConv, learning.sync.qlearning.discrete.
QLearningDiscreteConv).

The reference's Atari recipe: raw frames → grayscale → crop → downscale →
stack the last `historyLength` frames as the Q-net input, choose an
action every `skipFrame` frames (repeating it in between, summing the
reward). Frame munging is host-side numpy by nature (frames come from the
env on host); the Q-network itself is NHWC with the history stack as the
CHANNEL axis, so the first conv contracts history×space on the MXU in
one pass (the reference is NCHW with per-kernel CUDA dispatch)."""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.rl.dqn import (DQNPolicy, EpsGreedy,
                                       QLearningConfiguration,
                                       td_learn_batch)
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition


class HistoryProcessorConfiguration:
    """≡ learning.HistoryProcessor.Configuration."""

    def __init__(self, historyLength=4, rescaledWidth=84, rescaledHeight=84,
                 croppingWidth=None, croppingHeight=None, offsetX=0,
                 offsetY=0, skipFrame=4):
        self.historyLength = int(historyLength)
        self.rescaledWidth = int(rescaledWidth)
        self.rescaledHeight = int(rescaledHeight)
        self.croppingWidth = croppingWidth    # None = full width
        self.croppingHeight = croppingHeight
        self.offsetX = int(offsetX)
        self.offsetY = int(offsetY)
        self.skipFrame = int(skipFrame)


def _nearest_resize(img, out_h, out_w):
    """Dependency-free nearest-neighbor resize (deterministic)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ri = (np.arange(out_h) * h // out_h).clip(0, h - 1)
    ci = (np.arange(out_w) * w // out_w).clip(0, w - 1)
    return img[ri][:, ci]


class HistoryProcessor:
    """≡ learning.HistoryProcessor — grayscale + crop + rescale + ring of
    the last `historyLength` processed frames."""

    def __init__(self, conf: HistoryProcessorConfiguration):
        self.conf = conf
        self._ring = None

    def preProcess(self, frame):
        """(H, W) | (H, W, C) uint8/float → (rh, rw) float32 in [0, 1]."""
        f = np.asarray(frame)
        if f.ndim == 3:                      # RGB → luminance
            f = f.astype(np.float32) @ np.array([0.299, 0.587, 0.114],
                                                np.float32)
        was_int = np.issubdtype(np.asarray(frame).dtype, np.integer)
        f = f.astype(np.float32)
        if was_int:
            # dtype-based, NOT value-based: a near-black uint8 frame must
            # get the same scale as a bright one
            f = f / 255.0
        c = self.conf
        ch = c.croppingHeight or (f.shape[0] - c.offsetY)
        cw = c.croppingWidth or (f.shape[1] - c.offsetX)
        f = f[c.offsetY:c.offsetY + ch, c.offsetX:c.offsetX + cw]
        return _nearest_resize(f, c.rescaledHeight, c.rescaledWidth)

    def record(self, frame):
        """Process a frame and push it into the history ring."""
        f = self.preProcess(frame)
        if self._ring is None:
            # cold start: fill the whole ring with the first frame so
            # getHistory() is valid from step 0 (≡ reference startMonitor)
            self._ring = [f] * self.conf.historyLength
        else:
            self._ring = self._ring[1:] + [f]

    add = record

    def getHistory(self):
        """(rescaledH, rescaledW, historyLength) float32 — NHWC-ready,
        newest frame in the LAST channel."""
        if self._ring is None:
            raise RuntimeError("HistoryProcessor: record() a frame first")
        return np.stack(self._ring, axis=-1)

    def reset(self):
        self._ring = None


class DQNConvNetworkConfiguration:
    """≡ network.configuration.NetworkConfiguration for the conv factory
    (filter/kernel/stride stacks are configurable so small test MDPs
    don't pay Atari-sized convs)."""

    def __init__(self, learningRate=2.5e-4, l2=0.0, updater=None,
                 filters=(16, 32), kernels=((8, 8), (4, 4)),
                 strides=((4, 4), (2, 2)), denseUnits=256):
        self.learningRate = learningRate
        self.l2 = l2
        self.updater = updater
        self.filters = tuple(filters)
        self.kernels = tuple(tuple(k) for k in kernels)
        self.strides = tuple(tuple(s) for s in strides)
        self.denseUnits = int(denseUnits)


class DQNFactoryStdConv:
    """≡ network.dqn.DQNFactoryStdConv — Atari-style conv Q-network."""

    def __init__(self, conf: DQNConvNetworkConfiguration = None):
        self.conf = conf or DQNConvNetworkConfiguration()

    def buildDQN(self, shape_hwc, num_actions, seed=123):
        c = self.conf
        h, w, ch = shape_hwc
        b = (NeuralNetConfiguration.Builder()
             .seed(seed)
             .updater(c.updater or Adam(c.learningRate))
             .weightInit("relu")
             .l2(c.l2)
             .list())
        for f, k, s in zip(c.filters, c.kernels, c.strides):
            b.layer(ConvolutionLayer(kernelSize=k, stride=s, nOut=f,
                                     convolutionMode="truncate",
                                     activation="relu"))
        b.layer(DenseLayer(nOut=c.denseUnits, activation="relu"))
        b.layer(OutputLayer(lossFunction="mse", nOut=num_actions,
                            activation="identity"))
        return MultiLayerNetwork(
            b.setInputType(InputType.convolutional(h, w, ch))
            .build()).init()


class QLearningDiscreteConv:
    """≡ QLearningDiscreteConv — sync (double-)DQN over a pixel MDP:
    HistoryProcessor frame pipeline + conv Q-net + frame-skip action
    repeat. Same TD machinery as QLearningDiscreteDense; observations in
    replay are the PROCESSED (h, w, history) stacks."""

    def __init__(self, mdp, net_factory=None, hp_conf=None, ql_conf=None):
        self.mdp = mdp
        self.conf = ql_conf or QLearningConfiguration()
        self.hp = HistoryProcessor(hp_conf or
                                   HistoryProcessorConfiguration())
        if net_factory is None or isinstance(net_factory,
                                             DQNConvNetworkConfiguration):
            net_factory = DQNFactoryStdConv(net_factory)
        hc = self.hp.conf
        shape = (hc.rescaledHeight, hc.rescaledWidth, hc.historyLength)
        self.num_actions = mdp.getActionSpace().getSize()
        self.net = net_factory.buildDQN(shape, self.num_actions,
                                        self.conf.seed)
        self.target = self.net.clone()
        self._rng = np.random.default_rng(self.conf.seed)
        self.replay = ExpReplay(self.conf.expRepMaxSize,
                                self.conf.batchSize, self.conf.seed)
        self.policy = EpsGreedy(self.conf, self._rng)
        self.step_count = 0
        self.epoch_rewards = []

    def getPolicy(self):
        return _ConvDQNPolicy(self.net, self.hp)

    def getHistoryProcessor(self):
        return self.hp

    def _learn_batch(self):
        td_learn_batch(self.net, self.target, self.replay, self.conf)

    def train(self):
        c = self.conf
        skip = max(1, self.hp.conf.skipFrame)
        while self.step_count < c.maxStep:
            frame = self.mdp.reset()
            self.hp.reset()
            self.hp.record(frame)
            obs = self.hp.getHistory()
            ep_reward, ep_steps = 0.0, 0
            while not self.mdp.isDone() and ep_steps < c.maxEpochStep \
                    and self.step_count < c.maxStep:
                q = np.asarray(self.net.output(obs[None]))[0]
                action = self.policy.nextAction(
                    q, self.mdp.getActionSpace())
                # frame-skip: repeat the action, accumulate reward
                reward = 0.0
                done = False
                for _ in range(skip):
                    frame, r, done, _ = self.mdp.step(action)
                    reward += r
                    if done:
                        break
                self.hp.record(frame)
                next_obs = self.hp.getHistory()
                self.replay.store(
                    Transition(obs, action, reward, next_obs, done))
                obs = next_obs
                ep_reward += reward
                ep_steps += 1
                self.step_count += 1
                if (self.step_count > c.updateStart
                        and len(self.replay) >= c.batchSize):
                    self._learn_batch()
                if self.step_count % c.targetDqnUpdateFreq == 0:
                    self.target.setParams(self.net.params())
            self.epoch_rewards.append(ep_reward)
        return self.epoch_rewards


class _ConvDQNPolicy(DQNPolicy):
    """Greedy play that runs raw frames through the history pipeline."""

    def __init__(self, network, hp):
        super().__init__(network)
        self.hp = hp

    def play(self, mdp, max_steps=10000):
        frame = mdp.reset()
        self.hp.reset()
        self.hp.record(frame)
        total = 0.0
        skip = max(1, self.hp.conf.skipFrame)
        for _ in range(max_steps):
            action = self.nextAction(self.hp.getHistory())
            done = False
            for _ in range(skip):
                frame, r, done, _ = mdp.step(action)
                total += r
                if done:
                    break
            self.hp.record(frame)
            if done:
                break
        return total
