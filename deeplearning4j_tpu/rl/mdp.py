"""MDP environments (≡ rl4j-core :: org.deeplearning4j.rl4j.mdp.MDP,
CartpoleNative, toy MDPs).

Native Python/numpy physics — environments are host-side by nature; only
the learner's network steps run on the accelerator.
"""
from __future__ import annotations

import numpy as np


class ObservationSpace:
    def __init__(self, shape, low=None, high=None):
        self.shape = tuple(shape)
        self.low, self.high = low, high


class DiscreteSpace:
    def __init__(self, size):
        self.size = int(size)

    def getSize(self):
        return self.size

    def randomAction(self, rng):
        return int(rng.integers(self.size))


class MDP:
    """≡ rl4j MDP interface: reset / step / isDone / close."""

    def getObservationSpace(self):
        return self.observation_space

    def getActionSpace(self):
        return self.action_space

    def reset(self):
        raise NotImplementedError

    def step(self, action):
        """-> (observation, reward, done, info)"""
        raise NotImplementedError

    def isDone(self):
        return self.done

    def close(self):
        pass

    def newInstance(self):
        return type(self)()


class CartpoleNative(MDP):
    """≡ rl4j :: mdp.CartpoleNative — classic cart-pole balance physics
    (4-dim state, 2 actions, +1 reward per step, 200-step cap)."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5          # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_THRESHOLD = 12 * 2 * np.pi / 360
    X_THRESHOLD = 2.4
    MAX_STEPS = 200

    def __init__(self, seed=0):
        self.observation_space = ObservationSpace((4,))
        self.action_space = DiscreteSpace(2)
        self._rng = np.random.default_rng(seed)
        self.done = True
        self.state = None
        self._steps = 0

    def reset(self):
        self.state = self._rng.uniform(-0.05, 0.05, size=4)
        self.done = False
        self._steps = 0
        return self.state.astype(np.float32)

    def step(self, action):
        x, x_dot, theta, theta_dot = self.state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot ** 2 * sintheta
                ) / total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta ** 2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costheta / total_mass
        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        self.done = bool(
            abs(x) > self.X_THRESHOLD
            or abs(theta) > self.THETA_THRESHOLD
            or self._steps >= self.MAX_STEPS)
        return self.state.astype(np.float32), 1.0, self.done, {}


class SimpleToy(MDP):
    """≡ rl4j :: mdp.toy.SimpleToy — a chain of N states where action 1
    advances (+1 reward at the end), action 0 resets. Optimal policy:
    always act 1. Deterministic → convergence is testable exactly."""

    def __init__(self, length=5):
        self.length = int(length)
        self.observation_space = ObservationSpace((self.length,))
        self.action_space = DiscreteSpace(2)
        self.done = True
        self.pos = 0

    def _obs(self):
        v = np.zeros(self.length, np.float32)
        v[self.pos] = 1.0
        return v

    def reset(self):
        self.pos = 0
        self.done = False
        return self._obs()

    def step(self, action):
        if action == 1:
            self.pos += 1
            reward = 0.1
        else:
            self.pos = 0
            reward = 0.0
        if self.pos >= self.length - 1:
            reward = 1.0
            self.done = True
            self.pos = self.length - 1
        return self._obs(), reward, self.done, {}


class PixelGridWorld(MDP):
    """Synthetic PIXEL MDP for the conv-DQN path (stands in for the
    reference's ALE screens, zero egress): the agent is a bright square
    on a 1-D track rendered as a (size·scale, size·scale) grayscale
    frame. Every move costs −0.01; reaching the right edge pays +1.0 and
    ends the episode. Optimal policy: always go right, as fast as
    possible — learnable ONLY from the pixels."""

    def __init__(self, size=6, scale=2, maxSteps=40, seed=0):
        self.size = int(size)
        self.scale = int(scale)
        self.maxSteps = int(maxSteps)
        px = self.size * self.scale
        self.observation_space = ObservationSpace((px, px))
        self.action_space = DiscreteSpace(2)
        self._rng = np.random.default_rng(seed)
        self.done = True
        self.pos = 0
        self._steps = 0

    def _frame(self):
        f = np.zeros((self.size, self.size), np.float32)
        f[self.size // 2, self.pos] = 1.0
        return np.kron(f, np.ones((self.scale, self.scale), np.float32))

    def reset(self):
        self.pos = 0
        self.done = False
        self._steps = 0
        return self._frame()

    def step(self, action):
        self._steps += 1
        reward = -0.01
        if action == 1:
            self.pos = min(self.pos + 1, self.size - 1)
        else:
            self.pos = max(self.pos - 1, 0)
        if self.pos >= self.size - 1:
            reward = 1.0
            self.done = True
        if self._steps >= self.maxSteps:
            self.done = True
        return self._frame(), reward, self.done, {}
