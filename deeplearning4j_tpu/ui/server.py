"""Training dashboard (≡ deeplearning4j-ui :: UIServer + the Play/Vertx
web dashboard).

Two forms, both dependency-free:
- `UIServer.getInstance().attach(storage)` then `start()` — a stdlib
  http.server on a background thread: `/` serves the dashboard page,
  `/stats` the JSON records the page polls every second.
- `render_static_html(storage, path)` — a self-contained HTML snapshot
  (inline SVG charts) for environments without an open port.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training</title>
<style>
body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa}
h1{font-size:18px} .chart{background:#fff;border:1px solid #ddd;
border-radius:6px;padding:12px;margin-bottom:16px}
svg{width:100%;height:220px}
.meta{color:#666;font-size:13px}
</style></head><body>
<h1>Training dashboard</h1>
<div class="meta" id="meta">waiting for stats…</div>
<div class="chart"><h2>Score vs iteration</h2><svg id="score"></svg></div>
<div class="chart"><h2>Iteration time (ms)</h2><svg id="time"></svg></div>
<script>
function poly(svg, xs, ys, color){
  const el = document.getElementById(svg);
  if (xs.length < 2){ return; }
  const W = el.clientWidth || 600, H = 220, P = 30;
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const sx = x => P + (x - xmin) / (xmax - xmin || 1) * (W - 2*P);
  const sy = y => H - P - (y - ymin) / (ymax - ymin || 1) * (H - 2*P);
  const pts = xs.map((x,i)=>sx(x)+","+sy(ys[i])).join(" ");
  el.innerHTML = `<polyline fill="none" stroke="${color}" stroke-width="1.5"
    points="${pts}"/><text x="4" y="12" font-size="11">${ymax.toFixed(4)}
    </text><text x="4" y="${H-6}" font-size="11">${ymin.toFixed(4)}</text>`;
}
async function tick(){
  const r = await fetch('/stats'); const recs = await r.json();
  if (recs.length){
    const last = recs[recs.length-1];
    document.getElementById('meta').textContent =
      `iteration ${last.iteration} · epoch ${last.epoch} · score ` +
      last.score.toFixed(6);
    poly('score', recs.map(r=>r.iteration), recs.map(r=>r.score), '#0a6');
    const t = recs.filter(r=>r.iterationTimeMs != null);
    poly('time', t.map(r=>r.iteration), t.map(r=>r.iterationTimeMs), '#06a');
  }
}
setInterval(tick, 1000); tick();
</script></body></html>"""


class UIServer:
    """≡ org.deeplearning4j.ui.api.UIServer (singleton surface)."""

    _instance = None

    def __init__(self):
        self._storages = []
        self._httpd = None
        self._thread = None
        self.port = None

    @classmethod
    def getInstance(cls):
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, storage):
        self._storages.append(storage)
        return self

    def detach(self, storage):
        self._storages.remove(storage)
        return self

    def start(self, port=9000):
        if self._httpd is not None:
            return self
        storages = self._storages

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/stats"):
                    recs = []
                    for s in storages:
                        recs.extend(s.all())
                    body = json.dumps(recs).encode()
                    ctype = "application/json"
                else:
                    body = _PAGE.encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
        return self


def render_static_html(storage, path):
    """Static dashboard snapshot: inline-SVG score/time charts."""
    recs = storage.all()

    def svg_line(xs, ys, color):
        if len(xs) < 2:
            return "<svg></svg>"
        W, H, P = 640, 220, 30
        xmin, xmax = min(xs), max(xs)
        ymin, ymax = min(ys), max(ys)
        def sx(x):
            return P + (x - xmin) / ((xmax - xmin) or 1) * (W - 2 * P)
        def sy(y):
            return H - P - (y - ymin) / ((ymax - ymin) or 1) * (H - 2 * P)
        pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
        return (f'<svg viewBox="0 0 {W} {H}">'
                f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
                f'points="{pts}"/>'
                f'<text x="4" y="12" font-size="11">{ymax:.4g}</text>'
                f'<text x="4" y="{H-6}" font-size="11">{ymin:.4g}</text>'
                f'</svg>')

    iters = [r["iteration"] for r in recs]
    scores = [r["score"] for r in recs]
    times = [(r["iteration"], r["iterationTimeMs"]) for r in recs
             if r.get("iterationTimeMs") is not None]
    html = ("<!DOCTYPE html><html><head><title>training snapshot</title>"
            "</head><body><h1>Training snapshot</h1>"
            f"<p>{len(recs)} records</p>"
            "<h2>Score</h2>" + svg_line(iters, scores, "#0a6"))
    if times:
        html += "<h2>Iteration time (ms)</h2>" + svg_line(
            [t[0] for t in times], [t[1] for t in times], "#06a")
    html += "</body></html>"
    with open(path, "w") as f:
        f.write(html)
    return path
