"""Training dashboard (≡ deeplearning4j-ui :: UIServer + the Play/Vertx
web dashboard).

Two forms, both dependency-free:
- `UIServer.getInstance().attach(storage)` then `start()` — a stdlib
  http.server on a background thread: `/` serves the dashboard page,
  `/stats` the JSON records the page polls every second, and
  `/metrics` the host-side monitoring registry in Prometheus text
  exposition format (see deeplearning4j_tpu.monitoring — jit compile
  histogram, device memory gauges, transfer/inference counters; the
  dashboard's Metrics tab renders the same scrape).
- device observability endpoints: `POST /profile?steps=k` arms a
  `monitoring.profiler.ProfileSession` over the next k training steps,
  `GET /profile` returns its status + the latest decoded per-op report,
  and `GET /steps` serves the step-time attribution flight recorder
  (records + percentile summary) — each with a dashboard tab.
- `GET /executables` — AOT serving-executable cache status
  (runtime/executables.py `status()`): every live store's entries with
  compile-vs-disk provenance, hit/miss tallies, and the persistent
  compilation cache tier split.
- `GET /generation` — autoregressive generation status
  (generation/server.py `status()`): per-server slot occupancy, cache
  rung, admission/retirement/token tallies, executable provenance.
- `GET /fleet` — fleet-router status (generation/fleet.py `status()`):
  per-replica health / burn rate / rung / slot + queue occupancy,
  routing and failover tallies, and the autoscale signal (queue depth
  x SLO burn → desired replica count).
- `GET /requests` / `GET /requests/<trace-id>` — request-scoped
  tracing (monitoring/requests.py): in-flight + recent per-request
  lifecycle timelines, with latency-histogram exemplars linking a bad
  p99 to the slow request behind it; `GET /trace` exports the merged
  Chrome trace (host spans + request lanes) for Perfetto.
- `GET /slo` — SLO tracker state (monitoring/slo.py): objectives,
  per-window burn rates, current breaches; breaches also flip
  `GET /health` to degraded with the objective named.
- In a multi-host run, process 0's `/metrics` serves the CLUSTER view
  (monitoring/cluster.py): every host's series labeled host="<pid>"
  plus host="cluster" aggregates from the coordination-KV snapshots.
- `GET /stragglers` — straggler attribution
  (monitoring/stragglers.py): per-host attributed step time from the
  published step-timeline digests, the max/median ratio, and the
  culprit host + phase; `/steps` on process 0 also carries every
  host's timeline digest under "hosts", and `/trace` gains one named
  training lane per host.
- `render_static_html(storage, path)` — a self-contained HTML snapshot
  (inline SVG charts) for environments without an open port.
"""
from __future__ import annotations

import json
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training</title>
<style>
body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa}
h1{font-size:18px} .chart{background:#fff;border:1px solid #ddd;
border-radius:6px;padding:12px;margin-bottom:16px}
svg{width:100%;height:220px}
.meta{color:#666;font-size:13px}
</style></head><body>
<h1>Training dashboard</h1>
<div class="meta" id="meta">waiting for stats…</div>
<div class="chart"><h2>Score vs iteration</h2><svg id="score"></svg></div>
<div class="chart"><h2>Iteration time (ms)</h2><svg id="time"></svg></div>
<div class="chart"><h2>log10 update:parameter ratio</h2>
<svg id="ratios"></svg><div class="meta" id="ratiokeys"></div></div>
<div class="chart"><h2>Activation histograms (latest)</h2>
<div id="hists"></div></div>
<div class="chart"><h2>t-SNE</h2><svg id="tsne" style="height:320px">
</svg><div class="meta" id="tsnemeta">no t-SNE data attached</div></div>
<div class="chart"><h2>Metrics (host-side monitoring)</h2>
<div class="meta">Prometheus exposition of the monitoring registry —
enable with <code>net.setListeners(MetricsListener())</code>; scrape at
<code>/metrics</code></div>
<pre id="metrics" style="max-height:320px;overflow:auto;font-size:12px">
monitoring disabled or no metrics yet</pre></div>
<div class="chart"><h2>Device profile (XLA per-op)</h2>
<div class="meta">On-demand jax.profiler window decoded to a per-op
table — arm with
<button onclick="armProfile()">profile next 3 steps</button> or
<code>POST /profile?steps=k</code>; also
<code>monitoring.profile_next_steps(k)</code></div>
<pre id="profile" style="max-height:360px;overflow:auto;font-size:12px">
no profile captured yet</pre></div>
<div class="chart"><h2>Generation (KV-cache decode)</h2>
<div class="meta">Continuous-batching autoregressive serving —
<code>GET /generation</code>; live while a GenerationServer runs</div>
<pre id="generation" style="max-height:240px;overflow:auto;font-size:12px">
no generation servers live</pre></div>
<div class="chart"><h2>Fleet (replica routing)</h2>
<div class="meta">Health-driven routing across GenerationServer
replicas — <code>GET /fleet</code>; per-replica health + burn rate,
failover tallies, and the autoscale signal</div>
<pre id="fleet" style="max-height:240px;overflow:auto;font-size:12px">
no fleet routers live</pre></div>
<div class="chart"><h2>Requests (trace timelines)</h2>
<div class="meta">Request-scoped tracing — <code>GET /requests</code>,
<code>GET /requests/&lt;trace-id&gt;</code>; p99 exemplars link
histogram tails to slow-request timelines; full merged Chrome trace at
<code>GET /trace</code></div>
<pre id="requests" style="max-height:240px;overflow:auto;font-size:12px">
no request timelines yet</pre></div>
<div class="chart"><h2>SLOs (burn rate)</h2>
<div class="meta">Declarative objectives on a multi-window burn-rate
rule — <code>GET /slo</code>; a breach flips <code>GET /health</code>
to degraded with the objective named</div>
<pre id="slo" style="max-height:160px;overflow:auto;font-size:12px">
no SLO tracker installed</pre></div>
<div class="chart"><h2>Step-time attribution (flight recorder)</h2>
<div class="meta">Per-step host phase breakdown (data_next / dispatch /
listeners + host-blocked and compile stalls) — <code>GET /steps</code>;
records appear while monitoring is enabled
(<code>MetricsListener()</code>)</div>
<pre id="steps" style="max-height:320px;overflow:auto;font-size:12px">
no step records yet</pre></div>
<div class="chart"><h2>Incidents (ops event journal)</h2>
<div class="meta">Correlated cross-subsystem incidents — raw events at
<code>GET /events</code>, incidents at <code>GET /incidents</code>;
post-mortem bundle on demand via <code>POST /debug/bundle</code></div>
<pre id="incidents" style="max-height:240px;overflow:auto;font-size:12px">
no incidents yet</pre></div>
<script>
const COLORS = ['#0a6','#06a','#a06','#a60','#60a','#6a0','#066','#660'];
function poly(svg, xs, ys, color){
  const el = document.getElementById(svg);
  if (xs.length < 2){ return; }
  const W = el.clientWidth || 600, H = 220, P = 30;
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const sx = x => P + (x - xmin) / (xmax - xmin || 1) * (W - 2*P);
  const sy = y => H - P - (y - ymin) / (ymax - ymin || 1) * (H - 2*P);
  const pts = xs.map((x,i)=>sx(x)+","+sy(ys[i])).join(" ");
  el.innerHTML = `<polyline fill="none" stroke="${color}" stroke-width="1.5"
    points="${pts}"/><text x="4" y="12" font-size="11">${ymax.toFixed(4)}
    </text><text x="4" y="${H-6}" font-size="11">${ymin.toFixed(4)}</text>`;
}
function multiPoly(svg, series){   // series: [{name, xs, ys, color}]
  const el = document.getElementById(svg);
  const all = series.flatMap(s=>s.ys);
  if (!all.length){ return; }
  const W = el.clientWidth || 600, H = 220, P = 30;
  const xs = series.flatMap(s=>s.xs);
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...all), ymax = Math.max(...all);
  const sx = x => P + (x - xmin) / (xmax - xmin || 1) * (W - 2*P);
  const sy = y => H - P - (y - ymin) / (ymax - ymin || 1) * (H - 2*P);
  el.innerHTML = series.map(s=>`<polyline fill="none" stroke="${s.color}"
    stroke-width="1.2" points="${s.xs.map((x,i)=>sx(x)+","+sy(s.ys[i]))
    .join(" ")}"/>`).join("") +
    `<text x="4" y="12" font-size="11">${ymax.toFixed(2)}</text>
     <text x="4" y="${H-6}" font-size="11">${ymin.toFixed(2)}</text>`;
}
function histSvg(h, title, color){
  const W = 300, H = 120, n = h.counts.length;
  const cmax = Math.max(...h.counts, 1);
  const bars = h.counts.map((c,i)=>`<rect x="${i*W/n}" width="${W/n-1}"
    y="${H-20-(H-24)*c/cmax}" height="${(H-24)*c/cmax}"
    fill="${color}"/>`).join("");
  return `<svg viewBox="0 0 ${W} ${H}" style="width:300px;height:120px">
    ${bars}<text x="2" y="${H-6}" font-size="10">${h.min.toFixed(2)}</text>
    <text x="${W-40}" y="${H-6}" font-size="10">${h.max.toFixed(2)}</text>
    <text x="2" y="10" font-size="10">${title}</text></svg>`;
}
async function tick(){
  const r = await fetch('/stats'); const recs = await r.json();
  if (recs.length){
    const last = recs[recs.length-1];
    document.getElementById('meta').textContent =
      `iteration ${last.iteration} · epoch ${last.epoch} · score ` +
      last.score.toFixed(6);
    poly('score', recs.map(r=>r.iteration), recs.map(r=>r.score), '#0a6');
    const t = recs.filter(r=>r.iterationTimeMs != null);
    poly('time', t.map(r=>r.iteration), t.map(r=>r.iterationTimeMs), '#06a');
    const withR = recs.filter(r=>r.updateRatios &&
                              Object.keys(r.updateRatios).length);
    if (withR.length){
      const keys = Object.keys(withR[withR.length-1].updateRatios);
      multiPoly('ratios', keys.map((k,i)=>({name:k,
        xs: withR.filter(r=>k in r.updateRatios).map(r=>r.iteration),
        ys: withR.filter(r=>k in r.updateRatios)
          .map(r=>Math.log10(r.updateRatios[k]+1e-12)),
        color: COLORS[i % COLORS.length]})));
      document.getElementById('ratiokeys').innerHTML = keys.map((k,i)=>
        `<span style="color:${COLORS[i%COLORS.length]}">${k}</span>`)
        .join(" · ");
    }
    const ah = last.activationHistograms || {};
    document.getElementById('hists').innerHTML = Object.keys(ah)
      .map((k,i)=>histSvg(ah[k], k, COLORS[i % COLORS.length])).join("");
  }
  try {
    const mr = await fetch('/metrics'); const mt = await mr.text();
    if (mt.trim()){
      document.getElementById('metrics').textContent = mt;
    }
  } catch (e) {}
  try {
    const pr = await fetch('/profile'); const pd = await pr.json();
    const el = document.getElementById('profile');
    if (pd.last && pd.last.report){
      const rep = pd.last.report;
      let txt = `captured ${rep.steps} steps · device self time ` +
        `${rep.device_self_ms.toFixed(3)} ms · ${rep.op_count} ops\n\n` +
        '   self ms   total ms      %  count  category     op\n';
      for (const r of rep.ops){
        txt += `${r.self_ms.toFixed(3).padStart(10)} ` +
          `${r.total_ms.toFixed(3).padStart(10)} ` +
          `${r.pct.toFixed(1).padStart(6)} ${String(r.count).padStart(6)}` +
          `  ${r.category.padEnd(12)} ${r.name.slice(0,70)}\n`;
      }
      el.textContent = txt;
    } else if (pd.active){
      el.textContent = `profiling: ${pd.active.state} ` +
        `(${pd.active.captured_steps}/${pd.active.steps} steps)`;
    }
  } catch (e) {}
  try {
    const gr = await fetch('/generation'); const gd = await gr.json();
    if (gd.servers && gd.servers.length){
      document.getElementById('generation').textContent =
        gd.servers.map(s =>
          `${s.decoder} [${s.state}]: slots ${s.active_slots}/` +
          `${s.slots} · rung ${s.rung} · queued ${s.queued} · ` +
          `tokens ${s.tokens} · admissions ${s.admissions} · ` +
          `retirements ${s.retirements} · errors ${s.errors} · ` +
          `replays ${s.replays} · restarts ${s.restarts} · ` +
          `degradations ${s.degradations}\n` +
          `  superstep k=${s.superstep} draft=${s.draft} · ` +
          `supersteps ${s.supersteps} · tok/dispatch ` +
          `${s.tokens_per_dispatch ?? '-'} · syncs/tok ` +
          `${s.host_syncs_per_token ?? '-'} · per-token p50 ` +
          `${s.per_token_p50_ms ?? '-'} ms p99 ` +
          `${s.per_token_p99_ms ?? '-'} ms · draft ok/ko ` +
          `${s.draft_accepts}/${s.draft_rejects}`).join("\n");
    }
  } catch (e) {}
  try {
    const fr = await fetch('/fleet'); const fd = await fr.json();
    if (fd.routers && fd.routers.length){
      document.getElementById('fleet').textContent =
        fd.routers.map(f =>
          f.replicas.map(r =>
            `${r.name} [${r.health}] burn ${r.burn_short}/` +
            `${r.burn_long} · slots ${r.active_slots}/${r.slots} · ` +
            `queued ${r.queued} · routed ${r.routed} · failovers ` +
            `${r.failovers} · replacements ${r.replacements}`
          ).join("\n") +
          `\n  fleet: submitted ${f.submitted} · completed ` +
          `${f.completed} · failovers ${f.failovers} · shed ` +
          `${f.shed} · desired replicas ` +
          `${f.autoscale.desired_replicas} (util ` +
          `${f.autoscale.utilization} x burn ` +
          `${f.autoscale.slo_burn})`).join("\n\n");
    }
  } catch (e) {}
  try {
    const rr = await fetch('/requests?last=12'); const rd = await rr.json();
    const rows = [...(rd.active||[]), ...(rd.recent||[]).slice().reverse()];
    if (rows.length){
      document.getElementById('requests').textContent = rows.map(t => {
        const last = t.events.length ? t.events[t.events.length-1] : null;
        const blocks = t.events.filter(e=>e.event==='block').length;
        return `${t.trace_id} [${t.kind}] ${t.status||'in-flight'} · ` +
          `${t.events.length} events · blocks ${blocks}` +
          (last ? ` · last ${last.event}@${last.t_ms.toFixed(1)}ms` : '');
      }).join("\n");
    }
  } catch (e) {}
  try {
    const lr = await fetch('/slo'); const ld = await lr.json();
    if (ld.installed && ld.objectives){
      document.getElementById('slo').textContent =
        Object.values(ld.objectives).map(o =>
          `${o.breached ? 'BREACH' : '  ok  '} ${o.name}: ` +
          `burn short ${o.burn_short} long ${o.burn_long} · ` +
          `last ${o.last_value==null?'-':o.last_value.toFixed(3)} ` +
          `(limit ${o.threshold})`).join("\n");
    }
  } catch (e) {}
  try {
    const sr = await fetch('/steps'); const sd = await sr.json();
    const el = document.getElementById('steps');
    if (sd.summary && sd.summary.count){
      const s = sd.summary;
      let txt = `${s.count} steps`;
      if (s.wall_ms){ txt += ` · wall p50 ${s.wall_ms.p50.toFixed(2)} ms` +
        ` p95 ${s.wall_ms.p95.toFixed(2)} ms`; }
      if (s.coverage != null){
        txt += ` · attribution coverage ${(100*s.coverage).toFixed(0)}%`; }
      txt += '\n';
      for (const k in s.phases){
        const p = s.phases[k];
        txt += `  ${k}: p50 ${p.p50.toFixed(2)} ms  ` +
          `p95 ${p.p95.toFixed(2)} ms\n`;
      }
      txt += `  compiles: ${s.compile_count_total} ` +
        `(${s.compile_ms_total.toFixed(1)} ms) · host blocked ` +
        `${s.host_blocked_ms_total.toFixed(1)} ms\n\nlast steps:\n`;
      for (const r of sd.records.slice(-12)){
        const ph = Object.entries(r.phases)
          .map(([k,v])=>`${k}=${v.toFixed(2)}`).join(' ');
        txt += `  #${r.step} wall=` +
          (r.wall_ms==null?'?':r.wall_ms.toFixed(2)) + ` ms  ${ph}\n`;
      }
      el.textContent = txt;
    }
  } catch (e) {}
  try {
    const ir = await fetch('/incidents'); const id_ = await ir.json();
    const rows = [...(id_.open||[]), ...(id_.recent||[]).slice().reverse()];
    if (rows.length){
      document.getElementById('incidents').textContent = rows.map(i =>
        `${i.state==='open' ? 'OPEN  ' : 'closed'} ${i.id} · ` +
        `trigger ${i.trigger.kind} [${i.trigger.subsystem}] · ` +
        `${i.actions.length} actions · ` +
        `resolution ${i.resolution || '-'} · ` +
        (i.duration_s==null ? 'ongoing' :
         `${i.duration_s.toFixed(2)} s`)).join("\n");
    }
  } catch (e) {}
  const tr = await fetch('/tsne'); const td = await tr.json();
  if (td.points && td.points.length){
    const el = document.getElementById('tsne');
    const W = el.clientWidth || 600, H = 320, P = 20;
    const xs = td.points.map(p=>p[0]), ys = td.points.map(p=>p[1]);
    const xmin=Math.min(...xs), xmax=Math.max(...xs);
    const ymin=Math.min(...ys), ymax=Math.max(...ys);
    const labs = td.labels || [];
    const lset = [...new Set(labs)];
    el.innerHTML = td.points.map((p,i)=>`<circle
      cx="${P+(p[0]-xmin)/(xmax-xmin||1)*(W-2*P)}"
      cy="${H-P-(p[1]-ymin)/(ymax-ymin||1)*(H-2*P)}" r="2.5"
      fill="${lset.length ?
        COLORS[((lset.indexOf(labs[i]) % COLORS.length) + COLORS.length)
               % COLORS.length] : COLORS[0]}"/>`).join("");
    document.getElementById('tsnemeta').textContent =
      `${td.points.length} points` + (lset.length>1 ?
      ` · classes: ${lset.join(", ")}` : "");
  }
}
async function armProfile(){
  try { await fetch('/profile?steps=3', {method: 'POST'}); } catch (e) {}
}
setInterval(tick, 1000); tick();
</script></body></html>"""


class UIServer:
    """≡ org.deeplearning4j.ui.api.UIServer (singleton surface)."""

    _instance = None

    def __init__(self):
        self._storages = []
        self._httpd = None
        self._thread = None
        self.port = None
        self._tsne = {"points": [], "labels": []}

    @classmethod
    def getInstance(cls):
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, storage):
        self._storages.append(storage)
        return self

    def detach(self, storage):
        self._storages.remove(storage)
        return self

    def attachTsne(self, vectors, labels=None, maxIter=300, perplexity=30.0,
                   seed=0):
        """t-SNE tab (≡ the reference UI's word-vector t-SNE view): pass
        2-D coords directly, or higher-dim vectors to embed here via
        clustering.tsne (exact MXU gradients)."""
        import numpy as _np
        vectors = _np.asarray(vectors, _np.float32)
        if vectors.ndim != 2:
            raise ValueError(f"attachTsne expects (N, D), got "
                             f"{vectors.shape}")
        if vectors.shape[1] != 2:
            from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne
            vectors = (BarnesHutTsne.Builder().setMaxIter(int(maxIter))
                       .perplexity(perplexity).seed(seed).build()
                       .fit(vectors).getData())
        self._tsne = {
            "points": [[float(a), float(b)] for a, b in vectors],
            "labels": [str(l) for l in labels] if labels is not None else [],
        }
        return self

    def start(self, port=9000):
        if self._httpd is not None:
            return self
        storages = self._storages
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/stats"):
                    recs = []
                    for s in storages:
                        recs.extend(s.all())
                    body = json.dumps(recs).encode()
                    ctype = "application/json"
                elif self.path.startswith("/tsne"):
                    body = json.dumps(server._tsne).encode()
                    ctype = "application/json"
                elif self.path.startswith("/profile"):
                    # latest ProfileSession status/report; arming is the
                    # POST below. Import is local so a dashboard-only
                    # UIServer doesn't pull the profiler at startup.
                    from deeplearning4j_tpu.monitoring import \
                        profiler as _prof
                    body = json.dumps(_prof.status()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/steps"):
                    # step-time attribution flight recorder: ring records
                    # + percentile summary (monitoring/steps.py). The
                    # summary covers the WHOLE ring; records are bounded
                    # (?last=N, default 64) — the dashboard polls every
                    # second and renders only a short tail, so shipping
                    # all 512 ring entries per tick is waste
                    from deeplearning4j_tpu.monitoring import \
                        steps as _steps
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    try:
                        last = int(q.get("last", ["64"])[0])
                    except ValueError:
                        last = 64
                    rec = _steps.recorder()
                    doc = {"records": rec.records(last=last),
                           "summary": rec.summary()}
                    # cluster-aware on process 0 of a multi-host run:
                    # every host's published timeline digest rides
                    # alongside the local ring (sys.modules — serving
                    # /steps must not pull in the parallel stack)
                    coord_mod = sys.modules.get(
                        "deeplearning4j_tpu.parallel.coordination")
                    coord = getattr(coord_mod, "ACTIVE", None) \
                        if coord_mod else None
                    if coord is not None and coord.process_id == 0 \
                            and coord.num_processes > 1:
                        try:
                            from deeplearning4j_tpu.monitoring import \
                                stragglers as _sg
                            doc["hosts"] = {
                                str(pid): snap.get("timeline")
                                for pid, snap
                                in sorted(_sg.gather(coord).items())}
                        except Exception:  # noqa: BLE001
                            pass
                    body = json.dumps(doc).encode()
                    ctype = "application/json"
                elif self.path.startswith("/stragglers"):
                    # straggler attribution (monitoring/stragglers.py):
                    # per-host attributed step time from the published
                    # timelines, the max/median ratio, and the culprit
                    # host + phase. 404 without an active coordinator —
                    # a single-process run has no peers to skew against
                    coord_mod = sys.modules.get(
                        "deeplearning4j_tpu.parallel.coordination")
                    coord = getattr(coord_mod, "ACTIVE", None) \
                        if coord_mod else None
                    if coord is None:
                        self.send_response(404)
                        self.end_headers()
                        self.wfile.write(b"no active peer coordinator")
                        return
                    from deeplearning4j_tpu.monitoring import \
                        stragglers as _sg
                    att = _sg.attribution(coord)
                    if att is None:
                        att = {"hosts": {}, "published": 0,
                               "ratio": None, "median_step_ms": None,
                               "slowest": None,
                               "error": "coordination KV unreachable"}
                    body = json.dumps(att).encode()
                    ctype = "application/json"
                elif self.path.startswith("/executables"):
                    # AOT serving-executable cache status: per-store
                    # entries (signature + compile/disk provenance),
                    # hit/miss tallies, and the persistent-compile-
                    # cache tier split (runtime/executables.py)
                    from deeplearning4j_tpu.runtime import \
                        executables as _exe
                    body = json.dumps(_exe.status()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/generation"):
                    # autoregressive generation status: every live
                    # GenerationServer's slot occupancy, cache rung,
                    # admission/retirement/token tallies and its
                    # executable-store provenance (generation/server.py)
                    from deeplearning4j_tpu.generation import \
                        server as _gen
                    body = json.dumps(_gen.status()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/fleet"):
                    # fleet-router status (generation/fleet.py): every
                    # live router's per-replica health / burn rate /
                    # pressure rung / slot + queue occupancy, routing
                    # and failover tallies, and the autoscale signal
                    # (queue depth x SLO burn -> desired replicas)
                    from deeplearning4j_tpu.generation import \
                        fleet as _fleet
                    body = json.dumps(_fleet.status()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/requests"):
                    # request-scoped tracing (monitoring/requests.py):
                    # /requests = in-flight + recent ring (+ the
                    # latency-histogram exemplars that link into it);
                    # /requests/<trace-id> = one timeline (404 when it
                    # aged out); /requests?last=N bounds the ring tail
                    from deeplearning4j_tpu import monitoring as _mon
                    from deeplearning4j_tpu.monitoring import \
                        requests as _reqs
                    parsed = urllib.parse.urlparse(self.path)
                    parts = [p for p in parsed.path.split("/") if p]
                    if len(parts) > 1:
                        tl = _reqs.log().get(urllib.parse.unquote(
                            parts[1]))
                        if tl is None:
                            body = b'{"error": "unknown trace id"}'
                            self.send_response(404)
                            self.send_header("Content-Type",
                                             "application/json")
                            self.send_header("Content-Length",
                                             str(len(body)))
                            self.end_headers()
                            self.wfile.write(body)
                            return
                        body = json.dumps(tl.snapshot()).encode()
                    else:
                        q = urllib.parse.parse_qs(parsed.query)
                        try:
                            last = int(q.get("last", ["32"])[0])
                        except ValueError:
                            last = 32
                        doc = _reqs.log().snapshot(last=last)
                        reg = _mon.get_registry()
                        ex = {}
                        for name in (_mon.GEN_PER_TOKEN_MS,
                                     _mon.GEN_PREFILL_MS,
                                     _mon.INFERENCE_REQUEST_MS):
                            h = reg.get(name)
                            if h is not None:
                                e = h.exemplars()
                                if e:
                                    ex[name] = e
                        doc["exemplars"] = ex
                        body = json.dumps(doc).encode()
                    ctype = "application/json"
                elif self.path.startswith("/slo"):
                    # SLO tracker state: objectives, burn rates per
                    # window, current breaches (evaluation is driven
                    # from here, rate-limited by the tracker)
                    from deeplearning4j_tpu.monitoring import slo as _slo
                    t = _slo.ACTIVE
                    body = json.dumps(
                        {"installed": t is not None,
                         **(t.snapshot() if t is not None else {})}
                    ).encode()
                    ctype = "application/json"
                elif self.path.startswith("/trace"):
                    # merged Chrome trace: host-side spans (per-process
                    # metadata lanes) + every request timeline as its
                    # own lane — save and load in Perfetto
                    from deeplearning4j_tpu.monitoring import \
                        requests as _reqs
                    body = json.dumps(
                        _reqs.merged_chrome_trace()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/health"):
                    # training-guardian + stall-watchdog state
                    # (resilience.health_snapshot): 200 while healthy,
                    # 503 when stalled or diverged — load balancers and
                    # supervisors key off the status code alone
                    from deeplearning4j_tpu import resilience as _res
                    snap = _res.health_snapshot()
                    body = json.dumps(snap).encode()
                    code = 200 if snap["status"] in ("ok", "degraded") \
                        else 503
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                elif self.path.startswith("/events"):
                    # ops event journal tail (monitoring/events.py):
                    # ordered structured events across subsystems;
                    # /events?last=N bounds the tail
                    from deeplearning4j_tpu.monitoring import \
                        events as _ev
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    try:
                        last = int(q.get("last", ["64"])[0])
                    except ValueError:
                        last = 64
                    body = json.dumps(_ev.snapshot(last=last)).encode()
                    ctype = "application/json"
                elif self.path.startswith("/incidents"):
                    # correlated incidents: open + recently closed, each
                    # {trigger, actions, resolution, duration} linking
                    # through to /requests/<id> and /trace
                    from deeplearning4j_tpu.monitoring import \
                        events as _ev
                    body = json.dumps(_ev.incidents()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    # Prometheus scrape surface for the host-side
                    # monitoring registry; with monitoring ENABLED the
                    # core families + device memory gauges refresh per
                    # scrape (pull-model collectors). Disabled → serve
                    # whatever the registry holds WITHOUT touching jax:
                    # a dashboard-only UIServer must not initialize a
                    # backend (or poll memory_stats) from its 1 s tick.
                    from deeplearning4j_tpu import monitoring as _mon
                    reg = _mon.get_registry()
                    if _mon.enabled():
                        try:
                            _mon.bootstrap_core_metrics(reg)
                        except Exception:  # noqa: BLE001 — always serve
                            pass
                    body = None
                    # cluster metrics plane: in a multi-host run,
                    # process 0 serves EVERY host's series labeled
                    # host="<pid>" plus cluster aggregates
                    # (host="cluster") from the per-host snapshots on
                    # the coordination KV. sys.modules, never a fresh
                    # import: a dashboard-only process must not pull
                    # the parallel stack in from its 1 s tick.
                    import sys as _sys
                    _coord = _sys.modules.get(
                        "deeplearning4j_tpu.parallel.coordination")
                    c = _coord.ACTIVE if _coord is not None else None
                    if c is not None and c.num_processes > 1 \
                            and c.process_id == 0:
                        try:
                            from deeplearning4j_tpu.monitoring import \
                                cluster as _cluster
                            body = _cluster.cluster_prometheus_text(
                                c, reg).encode()
                        except Exception:  # noqa: BLE001 — always serve
                            body = None
                    if body is None:
                        body = reg.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    body = _PAGE.encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path.startswith("/profile"):
                    # arm an on-demand profiling window over the next k
                    # training steps of whatever trainer runs next
                    from deeplearning4j_tpu.monitoring import \
                        profiler as _prof
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    try:
                        steps = int(q.get("steps", ["3"])[0])
                    except ValueError:
                        steps = 3
                    session = _prof.profile_next_steps(steps=steps)
                    body = json.dumps({"armed": True,
                                       "steps": session.steps}).encode()
                    code = 200
                elif self.path.startswith("/debug/bundle"):
                    # on-demand post-mortem bundle: one JSON file with
                    # the event tail, incidents, metrics snapshot, step
                    # recorder, request ring, health and open spans —
                    # the same document crash dumps and stall reports
                    # write (monitoring/events.py bundle()). The output
                    # directory comes from DL4J_CRASH_DUMP_DIR (cwd
                    # otherwise), never from the request: a client-
                    # supplied path would let any caller of this
                    # unauthenticated endpoint create files anywhere
                    # the process can write.
                    from deeplearning4j_tpu.monitoring import \
                        events as _ev
                    p = _ev.write_bundle(headline="POST /debug/bundle")
                    body = json.dumps(
                        {"path": p,
                         "sections": list(_ev.BUNDLE_SECTIONS)}).encode()
                    code = 200 if p else 500
                else:
                    body = b'{"error": "unknown endpoint"}'
                    code = 404
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
        return self


def render_static_html(storage, path, tsne=None):
    """Static dashboard snapshot: inline-SVG score/time charts plus the
    round-5 panels — log10 update:parameter ratios, latest activation
    histograms, and an optional t-SNE scatter (tsne=(coords, labels))."""
    import math

    recs = storage.all()

    def svg_line(xs, ys, color):
        if len(xs) < 2:
            return "<svg></svg>"
        W, H, P = 640, 220, 30
        xmin, xmax = min(xs), max(xs)
        ymin, ymax = min(ys), max(ys)
        def sx(x):
            return P + (x - xmin) / ((xmax - xmin) or 1) * (W - 2 * P)
        def sy(y):
            return H - P - (y - ymin) / ((ymax - ymin) or 1) * (H - 2 * P)
        pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
        return (f'<svg viewBox="0 0 {W} {H}">'
                f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
                f'points="{pts}"/>'
                f'<text x="4" y="12" font-size="11">{ymax:.4g}</text>'
                f'<text x="4" y="{H-6}" font-size="11">{ymin:.4g}</text>'
                f'</svg>')

    iters = [r["iteration"] for r in recs]
    scores = [r["score"] for r in recs]
    times = [(r["iteration"], r["iterationTimeMs"]) for r in recs
             if r.get("iterationTimeMs") is not None]
    html = ("<!DOCTYPE html><html><head><title>training snapshot</title>"
            "</head><body><h1>Training snapshot</h1>"
            f"<p>{len(recs)} records</p>"
            "<h2>Score</h2>" + svg_line(iters, scores, "#0a6"))
    if times:
        html += "<h2>Iteration time (ms)</h2>" + svg_line(
            [t[0] for t in times], [t[1] for t in times], "#06a")

    colors = ["#0a6", "#06a", "#a06", "#a60", "#60a", "#6a0", "#066"]
    with_r = [r for r in recs if r.get("updateRatios")]
    if with_r:
        keys = sorted(with_r[-1]["updateRatios"])
        html += "<h2>log10 update:parameter ratio</h2>"
        for i, k in enumerate(keys):
            pts = [(r["iteration"],
                    math.log10(r["updateRatios"][k] + 1e-12))
                   for r in with_r if k in r["updateRatios"]]
            html += (f'<div>{k}</div>'
                     + svg_line([p[0] for p in pts], [p[1] for p in pts],
                                colors[i % len(colors)]))
    ah = next((r["activationHistograms"] for r in reversed(recs)
               if r.get("activationHistograms")), None)
    if ah:
        html += "<h2>Activation histograms (latest)</h2>"
        for i, (k, h) in enumerate(sorted(ah.items())):
            cmax = max(h["counts"]) or 1
            W, H, n = 300, 120, len(h["counts"])
            bars = "".join(
                f'<rect x="{j * W / n:.1f}" width="{W / n - 1:.1f}" '
                f'y="{H - 20 - (H - 24) * c / cmax:.1f}" '
                f'height="{(H - 24) * c / cmax:.1f}" '
                f'fill="{colors[i % len(colors)]}"/>'
                for j, c in enumerate(h["counts"]))
            html += (f'<h3>{k}</h3><svg viewBox="0 0 {W} {H}" '
                     f'width="{W}" height="{H}">{bars}'
                     f'<text x="2" y="{H - 6}" font-size="10">'
                     f'{h["min"]:.2f}</text>'
                     f'<text x="{W - 44}" y="{H - 6}" font-size="10">'
                     f'{h["max"]:.2f}</text></svg>')
    if tsne is not None:
        coords, labels = (tsne if isinstance(tsne, tuple)
                          else (tsne, None))
        import numpy as _np
        coords = _np.asarray(coords, _np.float32)
        lset = sorted(set(map(str, labels))) if labels is not None else []
        W, H, P = 640, 360, 20
        xmin, ymin = coords.min(0)
        xmax, ymax = coords.max(0)
        dots = "".join(
            f'<circle cx="{P + (cx - xmin) / ((xmax - xmin) or 1) * (W - 2 * P):.1f}" '
            f'cy="{H - P - (cy - ymin) / ((ymax - ymin) or 1) * (H - 2 * P):.1f}" '
            f'r="2.5" fill="'
            + (colors[lset.index(str(labels[i])) % len(colors)]
               if lset else colors[0]) + '"/>'
            for i, (cx, cy) in enumerate(coords))
        html += (f"<h2>t-SNE ({len(coords)} points)</h2>"
                 f'<svg viewBox="0 0 {W} {H}" width="{W}" '
                 f'height="{H}">{dots}</svg>')
    html += "</body></html>"
    with open(path, "w") as f:
        f.write(html)
    return path
