"""UI / monitoring (≡ deeplearning4j-ui)."""
from deeplearning4j_tpu.ui.stats import (FileStatsStorage,
                                         InMemoryStatsStorage, StatsListener)
from deeplearning4j_tpu.ui.server import UIServer, render_static_html

__all__ = ["FileStatsStorage", "InMemoryStatsStorage", "StatsListener",
           "UIServer", "render_static_html"]
