"""Training stats collection (≡ deeplearning4j-ui ::
org.deeplearning4j.ui.model.stats.StatsListener + the StatsStorage
hierarchy: InMemoryStatsStorage / FileStatsStorage).

Each iteration records score, timing, and per-layer parameter/update
summaries (the mean-magnitude ratios the reference's dashboard charts for
learning-rate tuning). Storage is JSON-native; FileStatsStorage appends
JSONL so a dashboard — live server or static HTML — can tail it.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


class InMemoryStatsStorage:
    """≡ InMemoryStatsStorage."""

    def __init__(self):
        self.records = []

    def put(self, record):
        self.records.append(record)

    def all(self):
        return list(self.records)

    def latest(self):
        return self.records[-1] if self.records else None


class FileStatsStorage(InMemoryStatsStorage):
    """≡ FileStatsStorage — JSONL append."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        if os.path.exists(self.path):
            with open(self.path) as f:
                self.records = [json.loads(ln) for ln in f if ln.strip()]

    def put(self, record):
        super().put(record)
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


class StatsListener(TrainingListener):
    """≡ StatsListener(statsStorage, frequency)."""

    def __init__(self, storage=None, frequency=1):
        self.storage = storage if storage is not None \
            else InMemoryStatsStorage()
        self.frequency = max(1, int(frequency))
        self._last_time = None

    def _param_summaries(self, model):
        out = {}
        params = getattr(model, "_params", None) or {}
        for lname, p in params.items():
            for pname, v in p.items():
                arr = np.asarray(v)
                out[f"{lname}_{pname}"] = {
                    "meanMagnitude": float(np.abs(arr).mean()),
                    "stdev": float(arr.std()),
                }
        return out

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency:
            return
        now = time.perf_counter()
        dt_ms = None if self._last_time is None else (
            (now - self._last_time) * 1000.0 / self.frequency)
        self._last_time = now
        record = {
            "iteration": int(iteration),
            "epoch": int(epoch),
            "timestamp": time.time(),
            "score": float(model.score()),
            "iterationTimeMs": dt_ms,
            "params": self._param_summaries(model),
        }
        self.storage.put(record)

    # -- convenience ------------------------------------------------------
    def scores(self):
        return [r["score"] for r in self.storage.all()]
