"""Training stats collection (≡ deeplearning4j-ui ::
org.deeplearning4j.ui.model.stats.StatsListener + the StatsStorage
hierarchy: InMemoryStatsStorage / FileStatsStorage).

Each iteration records score, timing, and per-layer parameter/update
summaries (the mean-magnitude ratios the reference's dashboard charts for
learning-rate tuning). Storage is JSON-native; FileStatsStorage appends
JSONL so a dashboard — live server or static HTML — can tail it.

Observability cross-links: StatsListener covers LEARNING diagnostics.
For HOST-side operational metrics and span tracing (where did the step's
wall time go; Prometheus `/metrics`; Chrome-trace export), opt in with
`optimize.listeners.MetricsListener` / `deeplearning4j_tpu.monitoring`;
for DEVICE-side per-op XLA traces use `optimize.listeners.
ProfilerListener` + `optimize/xplane.py`. All three can run together.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


class InMemoryStatsStorage:
    """≡ InMemoryStatsStorage."""

    def __init__(self):
        self.records = []

    def put(self, record):
        self.records.append(record)

    def all(self):
        return list(self.records)

    def latest(self):
        return self.records[-1] if self.records else None


class FileStatsStorage(InMemoryStatsStorage):
    """≡ FileStatsStorage — JSONL append."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        if os.path.exists(self.path):
            with open(self.path) as f:
                self.records = [json.loads(ln) for ln in f if ln.strip()]

    def put(self, record):
        super().put(record)
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


class StatsListener(TrainingListener):
    """≡ StatsListener(statsStorage, frequency).

    Round-5 depth (≡ the reference dashboard's TrainModule data): each
    record also carries per-layer-param update:parameter mean-magnitude
    RATIOS (the learning-rate-tuning chart; computed from the param delta
    since the previous record) and per-layer ACTIVATION histograms
    (forward pass over the most recent training batch, inference mode).
    Both can be disabled for minimal overhead."""

    def __init__(self, storage=None, frequency=1, collectRatios=True,
                 collectActivations=True, activationFrequency=10,
                 histogramBins=20):
        self.storage = storage if storage is not None \
            else InMemoryStatsStorage()
        self.frequency = max(1, int(frequency))
        self.collectRatios = bool(collectRatios)
        self.collectActivations = bool(collectActivations)
        # histograms cost an extra forward + host transfer: collect every
        # activationFrequency-th RECORD (first record included) so the
        # default overhead is ~1/10 of a forward pass, not 1x
        self.activationFrequency = max(1, int(activationFrequency))
        self.histogramBins = int(histogramBins)
        self._last_time = None
        self._prev_params = None
        self._record_idx = 0
        self._params_version_seen = None

    def _flat_params(self, model):
        """ONE device->host transfer of the parameter set; summaries and
        ratios both derive from this host copy.

        np.array (NOT np.asarray): on the CPU backend np.asarray(jax_arr)
        can return a zero-copy VIEW of the device buffer, and the donating
        train step rewrites that buffer in place on the next update — the
        "previous" snapshot would silently mutate to equal the current
        params and every update ratio would read exactly 0 (the reverse
        direction of the runtime/pipeline.py xla_owned_copy hazard)."""
        params = getattr(model, "_params", None) or {}
        return {f"{ln}_{pn}": np.array(v)
                for ln, p in params.items() for pn, v in p.items()}

    @staticmethod
    def _param_summaries(flat):
        return {k: {"meanMagnitude": float(np.abs(arr).mean()),
                    "stdev": float(arr.std())}
                for k, arr in flat.items()}

    def _update_ratios(self, flat):
        """mean|Δparam| / mean|param| per layer param — the reference
        dashboard's update:parameter ratio chart (healthy ≈ 1e-3)."""
        prev, self._prev_params = self._prev_params, flat
        if prev is None:
            return {}
        out = {}
        for k, arr in flat.items():
            p0 = prev.get(k)
            if p0 is None or p0.shape != arr.shape:
                continue
            pm = float(np.abs(arr).mean())
            out[k] = float(np.abs(arr - p0).mean() / (pm + 1e-12))
        return out

    def _activation_histograms(self, model):
        x = getattr(model, "_last_features", None)
        ff = getattr(model, "feedForward", None)
        if x is None or ff is None:
            return {}
        out = {}
        try:
            acts = ff(x)
            if isinstance(acts, dict):   # ComputationGraph: node -> act
                items = list(acts.items())
            else:                        # MultiLayerNetwork: per-layer list
                items = [(f"layer{i}", a) for i, a in enumerate(acts)]
            for key, a in items:
                arr = np.asarray(a.jax() if hasattr(a, "jax") else a,
                                 np.float32).ravel()
                finite = arr[np.isfinite(arr)]
                if finite.size == 0:   # diverged layer: record, don't die
                    out[key] = {"min": 0.0, "max": 0.0,
                                "counts": [0] * self.histogramBins,
                                "nonFinite": int(arr.size)}
                    continue
                lo, hi = float(finite.min()), float(finite.max())
                counts, _ = np.histogram(
                    finite, bins=self.histogramBins,
                    range=(lo, hi if hi > lo else lo + 1))
                h = {"min": lo, "max": hi, "counts": counts.tolist()}
                if finite.size != arr.size:
                    h["nonFinite"] = int(arr.size - finite.size)
                out[key] = h
        except Exception:   # noqa: BLE001 — stats must never kill training
            return out
        return out

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency:
            return
        now = time.perf_counter()
        dt_ms = None if self._last_time is None else (
            (now - self._last_time) * 1000.0 / self.frequency)
        self._last_time = now
        flat = self._flat_params(model)
        record = {
            "iteration": int(iteration),
            "epoch": int(epoch),
            "timestamp": time.time(),
            "score": float(model.score()),
            "iterationTimeMs": dt_ms,
            "params": self._param_summaries(flat),
        }
        # scanned fit() (stepsPerDispatch=k) fires k iterationDone calls
        # after ONE real param update; _params_version marks actual
        # updates so the k-1 inner records don't log zero ratios and
        # duplicate histograms
        version = getattr(model, "_params_version", None)
        params_fresh = version is None or \
            version != self._params_version_seen
        self._params_version_seen = version
        if self.collectRatios and params_fresh:
            record["updateRatios"] = self._update_ratios(flat)
        if self.collectActivations and params_fresh:
            # count FRESH records only, so the rate stays one histogram
            # per activationFrequency real updates under scanned fit too
            if self._record_idx % self.activationFrequency == 0:
                record["activationHistograms"] = \
                    self._activation_histograms(model)
            self._record_idx += 1
        self.storage.put(record)

    # -- convenience ------------------------------------------------------
    def scores(self):
        return [r["score"] for r in self.storage.all()]
