"""Serving-grade AOT executable store + bucket ladder + staging ring.

BENCH_r02 measured 42.7 s of warmup+compile before the first served
step: every novel input shape paid a live `jax.jit` trace on the
request path. This module removes host compiles (and host-owned input
aliasing) from serving entirely — the JAX analog of pre-captured CUDA
graphs (PAPERS.md "Hybrid JIT-CUDA Graph Optimization"), with
µ-cuDNN-style micro-batching (fixed shape buckets, split oversized
work) so the executable set is closed and finite.

Three pieces:

- **`ExecutableStore`** — per-model two-tier cache of ahead-of-time
  compiled forward executables (`jax.jit(...).lower().compile()`), one
  per bucketed input signature. Tier 0 is an in-process dict (the
  steady-state hot path: one dict get, zero locks). Tier 1 is a
  versioned on-disk cache of serialized executables
  (`jax.experimental.serialize_executable`, pickled with their arg
  treedefs) keyed by (model fingerprint, bucket signature, dtype,
  device flavour): a restarted replica `warmup()`s from disk in
  seconds — deserialize, no XLA compile. Entries that fail to load
  (corrupt, version/backend mismatch) fall back to a live compile and
  are rewritten; they NEVER crash serving. JAX's persistent
  compilation cache (`DL4J_COMPILE_CACHE`, wired via
  `configure_persistent_cache()`) backs live compiles as a third
  tier, shared with training jit misses.

- **`BucketLadder`** — the closed shape vocabulary: a sorted tuple of
  batch buckets (and, for sequence models, length buckets). Requests
  pad up to the smallest admitting bucket (with a validity mask);
  oversized batches SPLIT across max-bucket chunks instead of
  compiling a new shape, so the executable set stays finite.

- **`StagingRing`** — bounded ring of pre-staged device input buffers.
  Every host batch enters the device through `xla_owned_copy`
  (runtime/pipeline.py): the executable's donated input argument is
  always XLA-owned, never a zero-copy alias of numpy memory (the PR 2
  donation hazard), so dispatch can donate inputs with zero
  host-owned aliasing.

Observability (`dl4j.exec.*` / `dl4j.jit.persistent_*`, all behind the
enabled-guard) + `GET /executables` on the UIServer via `status()`.

Cache layout (versioned; bump LAYOUT_VERSION to invalidate):

    <DL4J_EXEC_CACHE>/v1/<device-flavour>/<model-fingerprint>/<sig>.exe

- device-flavour: backend + device_kind (+ host CPU feature hash on
  CPU — XLA:CPU serializes machine code; a foreign host must MISS,
  not SIGILL: util/hostkey.py);
- model-fingerprint: conf JSON + param/state shape-dtype trees + jax
  version, so a retrained SAME architecture reuses its executables but
  any structural change misses;
- <sig>.exe: pickled {"meta": ..., "blob": (payload, in_tree,
  out_tree)}; meta re-checked at load, mismatch → treated as corrupt.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
import warnings
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.runtime.pipeline import xla_owned_copy

__all__ = [
    "BucketLadder", "ExecutableStore", "FunctionStore", "StagingRing",
    "configure_persistent_cache", "forward_fn", "model_fingerprint",
    "persistent_cache_stats", "status",
]

#: bump to invalidate every on-disk serialized executable at once
LAYOUT_VERSION = "v1"
#: on-disk serialized-executable cache root ("" → in-process tiers only)
ENV_CACHE_DIR = "DL4J_EXEC_CACHE"
#: jax persistent compilation cache dir (third tier, shared w/ training)
ENV_COMPILE_CACHE = "DL4J_COMPILE_CACHE"

_STORES = weakref.WeakSet()   # live stores, aggregated by status()


# -- persistent compilation cache (third tier) -----------------------------
_pcache_lock = threading.Lock()
_pcache_configured = False
#: process-lifetime persistent-compile-cache tallies (plain ints so the
#: split is observable even with monitoring disabled). CAVEAT on
#: "misses": jax emits its cache_misses event only when it WRITES a new
#: entry — a compile under jax_persistent_cache_min_compile_time_secs /
#: min_entry_size is neither persisted nor counted. `requests` (every
#: compile that consulted the cache) is the honest denominator:
#: non-hits = requests - hits.
_pcache_counts = {"hits": 0, "misses": 0, "requests": 0}


def _on_jax_cache_event(name, **kw):
    """Bridge jax's compilation-cache monitoring events onto dl4j
    metrics: every XLA compile request either hit the persistent cache
    (cross-process warm) or paid a live compile (hit rate =
    persistent_hits / persistent_requests)."""
    if name == "/jax/compilation_cache/cache_hits":
        _pcache_counts["hits"] += 1
        which, help_ = _mon.JIT_PERSISTENT_HITS, \
            "persistent compilation cache hits (XLA compile skipped)"
    elif name == "/jax/compilation_cache/cache_misses":
        _pcache_counts["misses"] += 1
        which, help_ = _mon.JIT_PERSISTENT_MISSES, \
            "persistent-cache misses that wrote a NEW entry (compiles " \
            "under the min-compile-time/size thresholds are not " \
            "persisted and not counted here — see persistent_requests)"
    elif name == "/jax/compilation_cache/compile_requests_use_cache":
        _pcache_counts["requests"] += 1
        which, help_ = _mon.JIT_PERSISTENT_REQUESTS, \
            "XLA compile requests that consulted the persistent cache " \
            "(hits + live compiles)"
    else:
        return
    if _mon.enabled():
        _mon.get_registry().counter(which, help=help_).inc()


def configure_persistent_cache(directory=None, force=False):
    """Idempotently wire jax's persistent compilation cache.

    `directory` (or $DL4J_COMPILE_CACHE) becomes
    `jax_compilation_cache_dir`; an already-configured dir is respected
    unless `force`. Always registers the cache-event listener so
    `dl4j.jit.persistent_{hits,misses}` count the first-tier vs
    persistent-tier split for EVERY jit in the process (training
    included). Returns the effective cache dir (None = cache off)."""
    global _pcache_configured
    with _pcache_lock:
        if not _pcache_configured:
            try:
                # jax-internal hook: losing it on a future jax only
                # loses the hit/miss SPLIT, never the cache itself
                from jax._src import monitoring as _jmon
                _jmon.register_event_listener(_on_jax_cache_event)
            except Exception:  # noqa: BLE001
                pass
            _pcache_configured = True
        directory = directory or os.environ.get(ENV_COMPILE_CACHE) or None
        current = jax.config.jax_compilation_cache_dir
        if directory and (force or not current) and directory != current:
            jax.config.update("jax_compilation_cache_dir", directory)
            try:
                # jax binds the cache object at first use; re-point it
                # or a pre-initialized cache keeps the old directory
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:  # noqa: BLE001 — best effort across jax
                pass
            current = directory
        return current


def persistent_cache_stats():
    """{'hits': n, 'misses': n} for this process (monitoring-free)."""
    return dict(_pcache_counts)


# -- identity --------------------------------------------------------------
def device_flavour():
    """Short key for "an executable compiled here runs there". XLA:CPU
    serializes host machine code — key by CPU feature flags + jax build
    (util/hostkey.py) so a foreign host misses instead of SIGILLing;
    accelerators key by backend + device_kind + jax version."""
    backend = jax.default_backend()
    kind = jax.devices()[0].device_kind.replace(" ", "_")
    if backend == "cpu":
        from deeplearning4j_tpu.util.hostkey import host_cpu_key
        return f"cpu-{host_cpu_key()}"
    return f"{backend}-{kind}-jax{jax.__version__}"


def _shape_dtype_tree(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (str(treedef),
            tuple((tuple(l.shape), str(jnp.result_type(l)))
                  for l in leaves))


def model_fingerprint(model):
    """Identity of the model's TRACE: configuration + parameter/state
    structure (+ compute dtype). Parameter VALUES are executable
    arguments, so a retrained model reuses its cached executables;
    any conf or shape change produces a different fingerprint."""
    try:
        conf_s = model.conf.toJson()
    except Exception:  # noqa: BLE001 — conf not JSON-able: repr identity
        conf_s = repr(getattr(model, "conf", type(model).__name__))
    parts = (type(model).__name__, conf_s,
             str(getattr(model, "_compute_dtype", "float32")),
             _shape_dtype_tree(getattr(model, "_params", {})),
             _shape_dtype_tree(getattr(model, "_state", {})))
    return hashlib.sha256(repr(parts).encode()).hexdigest()[:16]


def forward_fn(model, with_mask=False):
    """Pure inference forward `(params, state, *xs[, mask]) -> (y, ...)`
    suitable for AOT lowering — same trace the jitted train step uses,
    minus loss/grad. `with_mask` appends a (B, T) validity mask input
    (length-bucketed sequence serving). Returns a TUPLE of outputs."""
    is_graph = hasattr(model, "outputSingle")   # ComputationGraph
    if is_graph:
        input_names = list(model.conf.input_names)
        output_names = list(model.conf.output_names)

        def fwd(params, state, *args):
            mask = args[len(input_names)] if with_mask else None
            ins = dict(zip(input_names, args))
            fmasks = ({n: mask for n in input_names} if with_mask
                      else None)
            acts, _, _ = model._forward(params, state, ins, False, None,
                                        fmasks)
            return tuple(acts[n] for n in output_names)
    else:
        def fwd(params, state, *args):
            mask = args[1] if with_mask else None
            y, _, _, _ = model._forward(params, state, args[0], False,
                                        None, mask=mask)
            return (y,)
    return fwd


# -- bucket ladder ---------------------------------------------------------
class BucketLadder:
    """The serving shape vocabulary: batch buckets + optional sequence
    length buckets. `bucket(n)` → smallest batch bucket admitting n
    rows (None: oversized, split via `chunks(n)`); `length_bucket(t)`
    → smallest length bucket ≥ t. A sequence LONGER than the top rung
    serves at its native length (one extra cached executable — size
    the top rung to the longest supported input); the batch axis can
    split across dispatches, the time axis cannot."""

    def __init__(self, batch=(1, 2, 4, 8, 16, 32), length=None):
        self.batch = tuple(sorted({int(b) for b in batch}))
        if not self.batch or self.batch[0] < 1:
            raise ValueError(f"batch buckets must be >= 1: {batch}")
        self.length = (None if length is None
                       else tuple(sorted({int(t) for t in length})))
        if self.length is not None and self.length[0] < 1:
            raise ValueError(f"length buckets must be >= 1: {length}")

    @property
    def max_batch(self):
        return self.batch[-1]

    def bucket(self, n):
        for b in self.batch:
            if n <= b:
                return b
        return None

    def chunks(self, n):
        """Row counts of the dispatches serving an n-row batch: greedy
        max-bucket chunks + one bucketed remainder (µ-cuDNN's
        micro-batch split — never a novel shape)."""
        out = []
        while n > self.max_batch:
            out.append(self.max_batch)
            n -= self.max_batch
        if n:
            out.append(n)
        return out

    def length_bucket(self, t):
        if self.length is None:
            return t
        for b in self.length:
            if t <= b:
                return b
        return t   # over-long: native length, never truncate

    def __repr__(self):
        return f"BucketLadder(batch={self.batch}, length={self.length})"


# -- the stores ------------------------------------------------------------
class _Entry:
    __slots__ = ("call", "source", "cost")

    def __init__(self, call, source):
        self.call = call            # compiled/loaded executable
        self.source = source        # "compile" | "disk"
        self.cost = None            # {"flops","bytes_accessed"} or None


def _cost_of(call):
    """XLA's static cost analysis for one compiled executable:
    {"flops", "bytes_accessed"} floats, or None when the backend
    doesn't expose it. Pure host metadata — no dispatch, no sync."""
    try:
        ca = call.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        bytes_accessed = float(ca.get("bytes accessed", 0.0))
        if flops <= 0.0 and bytes_accessed <= 0.0:
            return None
        return {"flops": flops, "bytes_accessed": bytes_accessed}
    except Exception:  # noqa: BLE001 — cost is advisory, never fatal
        return None


class _AotStoreBase:
    """Shared two-tier AOT executable machinery: tier 0 in-process dict
    (the hot path: one dict get, no locks), tier 1 versioned on-disk
    serialized executables under the shared cache layout. Subclasses
    supply WHAT gets lowered (a model forward, a named decode
    function); this base owns identity, the memory→disk→compile
    resolution flow, persistence, and stats."""

    kind = "aot"

    def __init__(self, fingerprint, directory=None):
        self.directory = (os.environ.get(ENV_CACHE_DIR) or None
                          if directory is None else (directory or None))
        self.fingerprint = fingerprint
        self.flavour = device_flavour()
        self.trace_calls = 0        # times a python fn was traced
        self.stats = {"memory_hits": 0, "disk_hits": 0, "compiles": 0,
                      "deserialize_failures": 0, "serialize_failures": 0}
        self._mem = {}
        self._lock = threading.Lock()
        # third tier: live compiles (cache-layout misses) still warm
        # the cross-process persistent compilation cache
        configure_persistent_cache()
        _STORES.add(self)

    def _counted(self, fwd):
        def run(*args):
            self.trace_calls += 1   # once per TRACE, never per call
            return fwd(*args)
        return run

    # -- hot path ---------------------------------------------------------
    def lookup(self, key):
        """Steady state: one dict get, no locks, no jax."""
        e = self._mem.get(key)
        if e is None:
            return None
        self.stats["memory_hits"] += 1
        return e

    # -- miss path (boundary: the lint stops descending here) -------------
    def _entry_path(self, key):
        h = hashlib.sha256(repr(key).encode()).hexdigest()[:24]
        return os.path.join(self.directory, LAYOUT_VERSION, self.flavour,
                            self.fingerprint, h + ".exe")

    def _meta(self):
        return {"layout": LAYOUT_VERSION, "jax": jax.__version__,
                "backend": jax.default_backend(), "flavour": self.flavour,
                "fingerprint": self.fingerprint}

    def _count(self, name, help_):
        if _mon.enabled():
            _mon.get_registry().counter(name, help=help_).inc()

    def _note_cost(self, key, e):
        """Record the executable's static cost once, at compile/load
        time (miss path only — the steady-state lookup never re-reads
        it): per-entry on the store status, and per-signature gauges so
        tokens/s has a FLOPs-per-dispatch denominator."""
        e.cost = _cost_of(e.call)
        if e.cost is not None and _mon.enabled():
            reg = _mon.get_registry()
            labels = {"store": self.kind, "signature": repr(key)[:120]}
            reg.gauge(_mon.EXEC_FLOPS, labels=labels,
                      help="XLA cost-analysis FLOPs per dispatch of "
                           "this cached executable").set(e.cost["flops"])
            reg.gauge(_mon.EXEC_BYTES_ACCESSED, labels=labels,
                      help="XLA cost-analysis bytes accessed per "
                           "dispatch of this cached executable") \
               .set(e.cost["bytes_accessed"])

    def _resolve(self, key, lower_fn):
        """Memory → disk (deserialize, no XLA compile) → live compile
        (persisted back), under the store lock. Corrupt or mismatched
        disk entries count `deserialize_failures` and fall through to
        the live compile — never crash, never go stale."""
        with self._lock:
            e = self._mem.get(key)
            if e is not None:
                self.stats["memory_hits"] += 1
                return e
            # chaos site: a fault here simulates a corrupt/unreachable
            # executable cache on the miss path (warmup or a novel
            # signature) — never the in-memory steady state above
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.fire(_faults.EXECUTABLES_LOAD)
            path = (self._entry_path(key) if self.directory else None)
            if path is not None and os.path.exists(path):
                e = self._load_disk(key, path)
                if e is not None:
                    self._mem[key] = e
                    self._note_cost(key, e)
                    return e
            e = self._compile_live(key, lower_fn, path)
            self._mem[key] = e
            self._note_cost(key, e)
            return e

    def _load_disk(self, key, path):
        try:
            with open(path, "rb") as f:
                rec = pickle.load(f)
            if rec.get("meta") != self._meta():
                raise ValueError(f"cache meta mismatch: {rec.get('meta')}")
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = rec["blob"]
            call = _se.deserialize_and_load(payload, in_tree, out_tree)
            self.stats["disk_hits"] += 1
            self._count(_mon.EXEC_DISK_HITS,
                        "serving executables deserialized from the "
                        "on-disk AOT cache (no XLA compile)")
            return _Entry(call, "disk")
        except Exception:  # noqa: BLE001 — any bad entry → live compile
            self.stats["deserialize_failures"] += 1
            self._count(_mon.EXEC_DESERIALIZE_FAILURES,
                        "corrupt/mismatched AOT cache entries (fell "
                        "back to live compile)")
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def _compile_live(self, key, lower_fn, path):
        t0 = time.perf_counter()
        compiled = lower_fn().compile()
        dt = time.perf_counter() - t0
        self.stats["compiles"] += 1
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.EXEC_COMPILES,
                        help="live serving-executable compiles (cold "
                             "cache or novel signature)").inc()
            reg.histogram(_mon.EXEC_COMPILE_SECONDS,
                          help="wall time of live serving compiles") \
               .observe(dt)
        e = _Entry(compiled, "compile")
        if path is not None and self._persist(key, path,
                                              compiled) == "broken":
            # A compile served from jax's persistent kernel cache
            # serializes an INCOMPLETE payload on XLA:CPU (the object
            # code is not re-embedded: "Symbols not found" at reload —
            # the round-trip check in _persist catches it in-process).
            # Force ONE fresh compile outside that cache and persist
            # it, so a restarted replica really does warm from disk
            # with zero compiles instead of silently degrading. Only
            # the broken-payload signature retries: a backend that
            # cannot serialize at all (or a failing write) keeps the
            # old count-and-move-on behavior — recompiling would buy
            # nothing there.
            fresh = self._compile_uncached(lower_fn)
            if fresh is not None \
                    and self._persist(key, path, fresh) is True:
                e = _Entry(fresh, "compile")
        return e

    @staticmethod
    def _compile_uncached(lower_fn):
        """Really recompile, bypassing BOTH jax compile caches.
        Two latches have to be broken: the in-memory compilation LRU
        would hand back the very same symbol-less executable without
        compiling at all (jax.clear_caches()), and jax latches its
        is-persistent-cache-used verdict process-globally, so the
        enable_compilation_cache(False) scope only takes effect after
        a reset_cache(); reset again afterwards so the next unrelated
        compile re-evaluates back to enabled. Cost: a process-wide
        jit-cache flush — acceptable on this path, which only runs at
        store warmup when a broken payload was already detected (later
        retraces recompile against the still-warm kernel cache)."""
        try:
            from jax._src import compilation_cache as _cc
            from jax._src.config import enable_compilation_cache
            try:
                with enable_compilation_cache(False):
                    _cc.reset_cache()
                    jax.clear_caches()
                    return lower_fn().compile()
            finally:
                _cc.reset_cache()
        except Exception:  # noqa: BLE001 — keep the cached compile
            return None

    def _persist(self, key, path, compiled):
        """Serialize + verify + write one entry. Returns True when the
        entry was written, "broken" when serialization produced an
        UNLOADABLE payload (the deserialize_and_load round-trip failed
        — the kernel-cache incomplete-payload signature, worth a fresh
        recompile), or False when the backend cannot serialize / the
        write failed (nothing a recompile would change). An unloadable
        payload is never written to disk."""
        try:
            from jax.experimental import serialize_executable as _se
            blob = _se.serialize(compiled)
        except Exception:  # noqa: BLE001 — backend may not serialize
            self._count_serialize_failure()
            return False
        try:
            # round-trip check: deserialization failures surface HERE,
            # at persist time, not as a mystery on the next replica
            _se.deserialize_and_load(*blob)
        except Exception:  # noqa: BLE001 — incomplete payload
            self._count_serialize_failure()
            return "broken"
        try:
            rec = {"meta": self._meta(), "key": key, "blob": blob}
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(rec, f)
            os.replace(tmp, path)   # atomic: readers see whole files
            return True
        except Exception:  # noqa: BLE001 — unwritable cache dir
            self._count_serialize_failure()
            return False

    def _count_serialize_failure(self):
        self.stats["serialize_failures"] += 1
        self._count(_mon.EXEC_SERIALIZE_FAILURES,
                    "serving executables that could not be "
                    "serialized to disk (in-process cache only)")

    @staticmethod
    def _entry_status(k, e):
        d = {"signature": repr(k), "source": e.source}
        if e.cost is not None:
            d["flops"] = e.cost["flops"]
            d["bytes_accessed"] = e.cost["bytes_accessed"]
            d["cost"] = ("%.3g MFLOPs / %.3g MB per dispatch"
                         % (e.cost["flops"] / 1e6,
                            e.cost["bytes_accessed"] / 1e6))
        return d

    def status(self):
        return {"kind": self.kind,
                "fingerprint": self.fingerprint,
                "flavour": self.flavour,
                "directory": self.directory,
                "entries": [self._entry_status(k, e)
                            for k, e in sorted(self._mem.items(),
                                               key=lambda kv: repr(kv[0]))],
                "trace_calls": self.trace_calls,
                **self.stats}


class ExecutableStore(_AotStoreBase):
    """Two-tier AOT executable cache for ONE model's serving forward.

    Hot path: `lookup(sig)` — a dict get. Miss path (the ONLY place a
    trace or compile may happen; scripts/check_fastpath.py enforces
    that the serving hot path never reaches past `lookup`):
    `load_or_compile(sig)` under a lock — disk tier first, live
    `jit().lower().compile()` last, serialized back to disk."""

    kind = "model-forward"

    def __init__(self, model, directory=None, donate_inputs=True):
        self.model = model
        self.donate_inputs = bool(donate_inputs)
        super().__init__(model_fingerprint(model), directory=directory)
        # masked variant: (B, T) validity mask appended after the
        # inputs (length-bucketed sequence serving pads the time axis)
        self._fwds = {
            False: self._counted(forward_fn(model, with_mask=False)),
            True: self._counted(forward_fn(model, with_mask=True))}

    # -- hot path ---------------------------------------------------------
    def lookup(self, sig, with_mask=False):
        """Steady state: one dict get, no locks, no jax."""
        return super().lookup((sig, with_mask))

    # -- miss path (boundary: the lint stops descending here) -------------
    def _abstract_args(self, sig, with_mask):
        sds = jax.ShapeDtypeStruct
        as_sds = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda l: sds(jnp.shape(l), jnp.result_type(l)), t)
        xs = [sds(shape, jnp.dtype(dt)) for shape, dt in sig]
        if with_mask:
            # (B, T) validity mask over the first (sequence) input
            xs.append(sds(tuple(sig[0][0][:2]), jnp.dtype("float32")))
        return (as_sds(self.model._params), as_sds(self.model._state),
                *xs)

    def _lower(self, sig, with_mask):
        """Trace + lower (no XLA compile). Inputs (incl. the mask) are
        donated so dispatch reuses the staged XLA-owned buffers."""
        args = self._abstract_args(sig, with_mask)
        donate = (tuple(range(2, len(args))) if self.donate_inputs
                  else ())
        with warnings.catch_warnings():
            # XLA:CPU ignores donation ("donated buffers were not
            # usable") — harmless here, load-bearing on TPU
            warnings.simplefilter("ignore", UserWarning)
            return jax.jit(self._fwds[with_mask],
                           donate_argnums=donate).lower(*args)

    def load_or_compile(self, sig, with_mask=False):
        """Resolve one bucketed signature through the base tiers."""
        return self._resolve((sig, with_mask),
                             lambda: self._lower(sig, with_mask))

    # -- warmup / status --------------------------------------------------
    def warmup(self, sigs):
        """Pre-resolve signatures (the bucket ladder) — each either a
        bare sig or a (sig, with_mask) pair. Disk entries deserialize;
        only truly novel signatures compile. Returns
        {compiled, from_disk, seconds}."""
        before_c = self.stats["compiles"]
        before_d = self.stats["disk_hits"]
        t0 = time.perf_counter()
        for s in sigs:
            if (isinstance(s, tuple) and len(s) == 2
                    and isinstance(s[1], bool)):
                self.load_or_compile(s[0], with_mask=s[1])
            else:
                self.load_or_compile(s)
        return {"compiled": self.stats["compiles"] - before_c,
                "from_disk": self.stats["disk_hits"] - before_d,
                "seconds": time.perf_counter() - t0}

    def status(self):
        base = super().status()
        base["model"] = type(self.model).__name__
        base["entries"] = [dict(self._entry_status(k, e),
                                signature=repr(k[0]), masked=k[1])
                           for k, e in sorted(self._mem.items(),
                                              key=lambda kv: repr(kv[0]))]
        return base


class FunctionStore(_AotStoreBase):
    """Two-tier AOT cache of NAMED functions (the generation decode
    path: step / admit / retire / grow executables, one per cache-rung
    or prompt-bucket signature).

    `register(name, fn, donate_argnums=...)` declares the traceable;
    `load_or_compile((name, ...), example_args)` lowers it against the
    example's shapes/dtypes with the declared donation and runs it
    through the same memory → disk → live-compile tiers as
    ExecutableStore (so a restarted generation replica warms from disk
    in deserialize time). The hot path is `lookup(key)` — one dict get;
    the serving/decode-loop lints hold the trace boundary here too."""

    kind = "function"

    def __init__(self, fingerprint, directory=None):
        super().__init__(fingerprint, directory=directory)
        self._fns = {}

    def register(self, name, fn, donate_argnums=()):
        self._fns[name] = (self._counted(fn), tuple(donate_argnums))
        return self

    # -- miss path (boundary: the lint stops descending here) -------------
    def _lower_named(self, name, example_args):
        fn, donate = self._fns[name]
        sds = jax.ShapeDtypeStruct
        abstract = jax.tree_util.tree_map(
            lambda l: sds(jnp.shape(l), jnp.result_type(l)), example_args)
        with warnings.catch_warnings():
            # XLA:CPU ignores donation — harmless there, load-bearing
            # on TPU (the decode state is donated through every step)
            warnings.simplefilter("ignore", UserWarning)
            return jax.jit(fn, donate_argnums=donate).lower(*abstract)

    def load_or_compile(self, key, example_args):
        """key: (name, *static identity); example_args: concrete or
        ShapeDtypeStruct positional args the executable will be called
        with. Resolves through memory → disk → live compile."""
        name = key[0]
        if name not in self._fns:
            raise KeyError(f"no function registered under {name!r}")
        return self._resolve(
            key, lambda: self._lower_named(name, tuple(example_args)))


def status():
    """Aggregate cache status for every live store (GET /executables)."""
    return {"stores": [s.status() for s in list(_STORES)],
            "persistent_compile_cache": {
                "directory": jax.config.jax_compilation_cache_dir,
                **persistent_cache_stats()}}


# -- pre-staged device input ring ------------------------------------------
class StagingRing:
    """Bounded ring of pre-staged device input buffers.

    Every buffer is produced by `xla_owned_copy` — an XLA-owned copy,
    never a zero-copy alias of numpy memory — so the dispatch may
    DONATE it (the executable reuses the input allocation for outputs)
    with zero host-owned aliasing: the exact hazard class PR 2
    root-caused (donated alias → free() of numpy-owned memory).

    `stage()` RETURNS the staged buffers to the caller — each thread
    dispatches exactly what it staged, so concurrent dispatchers (a
    degraded multi-waiter fallback, shutdown's drain racing a live
    collector) can never serve each other's inputs. The ring only
    bounds how many staged batches may be in flight at once; the
    caller `release()`s its slot once dispatch has consumed (donated)
    the buffers."""

    def __init__(self, depth=2):
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._free = threading.Semaphore(self.depth)
        self._in_flight = 0
        self.staged = 0     # lifetime stages

    def stage(self, host_arrays, block=True):
        """Copy host (numpy) arrays into fresh XLA-owned device buffers
        and return them. Blocks while `depth` batches are already in
        flight (dispatch is behind) unless block=False (then None)."""
        if not self._free.acquire(blocking=block):
            return None
        bufs = tuple(xla_owned_copy(np.asarray(a)) for a in host_arrays)
        with self._lock:
            self._in_flight += 1
            occupancy = self._in_flight
            self.staged += 1
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.SERVING_STAGED_BUFFERS,
                        help="input batches staged into XLA-owned "
                             "device buffers").inc()
            reg.gauge(_mon.SERVING_STAGING_OCCUPANCY,
                      help="staged-but-undispatched ring slots") \
               .set(occupancy)
        return bufs

    def release(self):
        """Free one slot — the staged buffers were dispatched (and
        donated: the executable owns their memory now)."""
        with self._lock:
            if self._in_flight == 0:
                return          # tolerate unmatched release
            self._in_flight -= 1
            occupancy = self._in_flight
        self._free.release()
        if _mon.enabled():
            _mon.get_registry().gauge(
                _mon.SERVING_STAGING_OCCUPANCY,
                help="staged-but-undispatched ring slots") \
                .set(occupancy)

    def __len__(self):
        with self._lock:
            return self._in_flight
