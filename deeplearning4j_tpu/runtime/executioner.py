"""OpExecutioner facade (≡ nd4j NativeOpExecutioner / CudaExecutioner).

The reference routes every op through an executioner that picks kernels and
manages streams. Under XLA the executioner's real job collapses into: (a)
the jit dispatch cache (trace once per shape signature), (b) profiling
hooks. This facade exposes both with the reference's vocabulary, so code
written against `Nd4j.getExecutioner()` has a direct counterpart.
"""
from __future__ import annotations

import collections
import time

import jax

from deeplearning4j_tpu import monitoring as _mon


class OpExecutioner:
    _instance = None

    def __init__(self):
        self._jit_cache = {}
        self.profiling = False
        self.op_counts = collections.Counter()
        self.op_times = collections.defaultdict(float)
        # (registry, generation, dispatches, misses, compile_hist)
        self._mon_handles = None
        # cross-process warm compiles: point jax's persistent
        # compilation cache at $DL4J_COMPILE_CACHE (respecting an
        # already-configured dir) and bridge its hit/miss events onto
        # dl4j.jit.persistent_{hits,misses} — every dl4j.jit.cache_miss
        # then splits into "paid a live XLA compile" vs "deserialized
        # from the persistent tier" (runtime/executables.py)
        from deeplearning4j_tpu.runtime.executables import \
            configure_persistent_cache
        configure_persistent_cache()

    @classmethod
    def getInstance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    # -- dispatch --------------------------------------------------------
    def exec(self, fn, *args, static_argnums=(), **kwargs):
        """Execute fn under jit with executioner-level caching/profiling.

        With monitoring enabled, cache misses also feed the global
        MetricsRegistry: `dl4j.jit.cache_misses` (counter) and
        `dl4j.jit.compile_seconds` (histogram over the wall time of the
        miss dispatch — trace + XLA compile + first run, blocked to
        completion so the number is honest). The disabled path is the
        exact pre-monitoring fast path: dict hit, call, return."""
        key = (fn, static_argnums)
        jitted = self._jit_cache.get(key)
        miss = jitted is None
        if miss:
            jitted = jax.jit(fn, static_argnums=static_argnums)
            self._jit_cache[key] = jitted
        mon_on = _mon.enabled()
        if not (self.profiling or mon_on):
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        if self.profiling or miss:
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if self.profiling:
            name = getattr(fn, "__name__", str(fn))
            self.op_counts[name] += 1
            self.op_times[name] += dt
        if mon_on:
            # cache the registry handles (per-dispatch _get would pay a
            # lock + key build on the hottest path), but re-resolve when
            # the registry instance or its generation changed — after
            # clear() the old Counter objects are orphans that would
            # silently drop these series from /metrics
            reg = _mon.get_registry()
            h = self._mon_handles
            if h is None or h[0] is not reg or h[1] != reg.generation:
                h = self._mon_handles = (
                    reg, reg.generation,
                    reg.counter(_mon.OP_DISPATCHES),
                    reg.counter(_mon.JIT_CACHE_MISSES),
                    reg.histogram(_mon.JIT_COMPILE_SECONDS))
            h[2].inc()
            if miss:
                h[3].inc()
                h[4].observe(dt)
                # the flight recorder attributes compile stalls to the
                # step they landed in (monitoring/steps.py)
                _mon.step_recorder().on_compile(dt)
        return out

    def commit(self):
        """≡ flushing the op queue: wait for all device work."""
        for d in jax.devices():
            try:
                jax.device_put(0.0, d).block_until_ready()
            except Exception:
                pass

    # -- profiling (≡ OpProfiler) ---------------------------------------
    def setProfilingMode(self, enabled):
        self.profiling = bool(enabled)

    def getProfilingStats(self):
        return {name: {"count": self.op_counts[name],
                       "total_time_s": self.op_times[name]}
                for name in self.op_counts}

    def printEnvironmentInformation(self):
        info = {
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
            "jit_cache_entries": len(self._jit_cache),
        }
        for k, v in info.items():
            print(f"{k}: {v}")
        return info
