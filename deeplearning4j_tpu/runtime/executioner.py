"""OpExecutioner facade (≡ nd4j NativeOpExecutioner / CudaExecutioner).

The reference routes every op through an executioner that picks kernels and
manages streams. Under XLA the executioner's real job collapses into: (a)
the jit dispatch cache (trace once per shape signature), (b) profiling
hooks. This facade exposes both with the reference's vocabulary, so code
written against `Nd4j.getExecutioner()` has a direct counterpart.
"""
from __future__ import annotations

import collections
import time

import jax


class OpExecutioner:
    _instance = None

    def __init__(self):
        self._jit_cache = {}
        self.profiling = False
        self.op_counts = collections.Counter()
        self.op_times = collections.defaultdict(float)

    @classmethod
    def getInstance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    # -- dispatch --------------------------------------------------------
    def exec(self, fn, *args, static_argnums=(), **kwargs):
        """Execute fn under jit with executioner-level caching/profiling."""
        key = (fn, static_argnums)
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(fn, static_argnums=static_argnums)
        jitted = self._jit_cache[key]
        if not self.profiling:
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        name = getattr(fn, "__name__", str(fn))
        self.op_counts[name] += 1
        self.op_times[name] += time.perf_counter() - t0
        return out

    def commit(self):
        """≡ flushing the op queue: wait for all device work."""
        for d in jax.devices():
            try:
                jax.device_put(0.0, d).block_until_ready()
            except Exception:
                pass

    # -- profiling (≡ OpProfiler) ---------------------------------------
    def setProfilingMode(self, enabled):
        self.profiling = bool(enabled)

    def getProfilingStats(self):
        return {name: {"count": self.op_counts[name],
                       "total_time_s": self.op_times[name]}
                for name in self.op_counts}

    def printEnvironmentInformation(self):
        info = {
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
            "jit_cache_entries": len(self._jit_cache),
        }
        for k, v in info.items():
            print(f"{k}: {v}")
        return info
