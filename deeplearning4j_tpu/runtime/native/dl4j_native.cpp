// Native host-side data runtime (≡ the roles libnd4j + DataVec's native
// image pipeline play in the reference: record parsing, buffer conversion,
// batch assembly, async prefetch). The TPU compute path is XLA; this code
// feeds it from the host without holding the Python GIL (ctypes releases
// the GIL for the duration of each call, so the prefetch thread converts
// batches while Python dispatches device work).
//
// C ABI only — bound via ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// IDX (MNIST-family) parsing
// ---------------------------------------------------------------------------
// Reads an uncompressed IDX file. Returns malloc'd payload (caller frees via
// dl4j_free), fills dims[0..ndim). Returns nullptr on failure.
void* dl4j_idx_read(const char* path, int64_t* dims, int32_t* ndim,
                    int32_t* dtype_code) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  unsigned char hdr[4];
  if (fread(hdr, 1, 4, f) != 4 || hdr[0] != 0 || hdr[1] != 0) {
    fclose(f);
    return nullptr;
  }
  *dtype_code = hdr[2];
  int nd = hdr[3];
  *ndim = nd;
  int64_t total = 1;
  for (int i = 0; i < nd; i++) {
    unsigned char b[4];
    if (fread(b, 1, 4, f) != 4) { fclose(f); return nullptr; }
    dims[i] = ((int64_t)b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3];
    total *= dims[i];
  }
  size_t elem = (*dtype_code == 0x0D) ? 4 : (*dtype_code == 0x0E) ? 8 : 1;
  void* buf = malloc((size_t)total * elem);
  if (!buf) { fclose(f); return nullptr; }
  size_t got = fread(buf, elem, (size_t)total, f);
  fclose(f);
  if ((int64_t)got != total) { free(buf); return nullptr; }
  return buf;
}

void dl4j_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// Numeric CSV parsing (≡ datavec CSVRecordReader's hot path for all-numeric
// tables). Single pass, no allocation per field, GIL released by ctypes.
// ---------------------------------------------------------------------------
// First pass over a NUL-terminated buffer: number of data rows (after
// skip_rows, blank lines ignored) and columns of the first data row.
void dl4j_csv_dims(const char* buf, char delim, int32_t skip_rows,
                   int64_t* rows_out, int64_t* cols_out) {
  int64_t rows = 0, cols = 0;
  int32_t skipped = 0;
  const char* p = buf;
  while (*p) {
    const char* line = p;
    int64_t c = 1;
    while (*p && *p != '\n') {
      if (*p == delim) ++c;
      ++p;
    }
    int64_t linelen = p - line;
    if (*p) ++p;  // consume '\n'
    // skip counts PHYSICAL lines (matching the Python path, where the
    // csv module yields a row per line including blanks)
    if (skipped < skip_rows) { ++skipped; continue; }
    if (linelen == 0 || (linelen == 1 && line[0] == '\r')) continue;
    if (rows == 0) cols = c;
    ++rows;
  }
  *rows_out = rows;
  *cols_out = cols;
}

// Second pass: fill out[rows*cols] float32. A field parses as a number
// only when strtof consumes it EXACTLY (up to trailing spaces/'\r') —
// empty, partial ("1.5abc"), and out-of-bounds parses all become NaN, so
// the caller's NaN screen rejects files the Python float() path would
// raise on. Short rows pad with NaN; long rows truncate. Returns values
// written, or -1 if out would overflow.
int64_t dl4j_csv_parse(const char* buf, char delim, int32_t skip_rows,
                       int64_t rows, int64_t cols, float* out) {
  int32_t skipped = 0;
  int64_t r = 0, written = 0;
  const char* p = buf;
  while (*p && r < rows) {
    const char* line = p;
    while (*p && *p != '\n') ++p;
    int64_t linelen = p - line;
    const char* line_end = p;
    if (*p) ++p;
    if (skipped < skip_rows) { ++skipped; continue; }
    if (linelen == 0 || (linelen == 1 && line[0] == '\r')) continue;
    const char* q = line;
    for (int64_t c = 0; c < cols; ++c) {
      if (written >= rows * cols) return -1;
      float v = __builtin_nanf("");
      if (q <= line_end) {
        // bound the field FIRST: strtof treats tabs/spaces as leading
        // whitespace, so an empty whitespace-delimited field would
        // otherwise swallow the next field's (or line's) number
        const char* fe = q;
        while (fe < line_end && *fe != delim) ++fe;
        const char* te = fe;
        while (te > q && (te[-1] == ' ' || te[-1] == '\r')) --te;
        if (te > q) {
          // reject C99 hex floats: strtof accepts "0x1A" but Python's
          // float() raises, and strict parity is the whole contract
          const char* h = q;
          while (h < te && (*h == ' ' || *h == '\t')) ++h;
          if (h < te && (*h == '+' || *h == '-')) ++h;
          bool hex = (h + 1 < te && h[0] == '0'
                      && (h[1] == 'x' || h[1] == 'X'));
          if (!hex) {
            char* endp = nullptr;
            float parsed = strtof(q, &endp);
            if (endp > q && endp == te) v = parsed;  // exact consume only
          }
        }
        q = (fe < line_end) ? fe + 1 : line_end + 1;
      }
      out[written++] = v;
    }
    ++r;
  }
  return written;
}

// ---------------------------------------------------------------------------
// Buffer conversion / batch assembly
// ---------------------------------------------------------------------------
// uint8 -> float32 with affine scale: dst = src * scale + bias
void dl4j_u8_to_f32(const uint8_t* src, float* dst, int64_t n, float scale,
                    float bias) {
  for (int64_t i = 0; i < n; i++) dst[i] = (float)src[i] * scale + bias;
}

// Gather `batch` items of `item_size` bytes from a uint8 archive into a
// float32 batch buffer with scaling — one call assembles a whole minibatch
// (≡ DataVec's RecordReaderDataSetIterator hot loop, natively).
void dl4j_gather_batch_u8(const uint8_t* src, int64_t item_size,
                          const int64_t* indices, int64_t batch, float* dst,
                          float scale, float bias) {
  for (int64_t b = 0; b < batch; b++) {
    const uint8_t* item = src + indices[b] * item_size;
    float* out = dst + b * item_size;
    for (int64_t i = 0; i < item_size; i++)
      out[i] = (float)item[i] * scale + bias;
  }
}

// One-hot encode int labels into a float32 matrix (batch, n_classes).
void dl4j_one_hot(const uint8_t* labels, const int64_t* indices,
                  int64_t batch, int64_t n_classes, float* dst) {
  memset(dst, 0, sizeof(float) * (size_t)batch * n_classes);
  for (int64_t b = 0; b < batch; b++)
    dst[b * n_classes + labels[indices[b]]] = 1.0f;
}

// Channel-mean subtraction in-place on a float32 NHWC batch (vgg-style).
void dl4j_sub_channel_means(float* data, int64_t n_pixels, int64_t channels,
                            const float* means) {
  for (int64_t p = 0; p < n_pixels; p++)
    for (int64_t c = 0; c < channels; c++) data[p * channels + c] -= means[c];
}

// Standardize columns in-place: (x - mean) / std over a (rows, cols) f32.
void dl4j_standardize(float* data, int64_t rows, int64_t cols,
                      const float* mean, const float* std) {
  for (int64_t r = 0; r < rows; r++) {
    float* row = data + r * cols;
    for (int64_t c = 0; c < cols; c++) row[c] = (row[c] - mean[c]) / std[c];
  }
}

// ---------------------------------------------------------------------------
// Async prefetch ring (≡ AsyncDataSetIterator's workspace-backed queue).
// The producer thread runs a registered C callback? No — Python drives
// production; the ring just provides a bounded, lock-protected handoff of
// opaque buffers so the conversion work above can happen off the consumer's
// critical path.
// ---------------------------------------------------------------------------
struct Ring {
  std::queue<std::pair<void*, int64_t>> q;
  std::mutex m;
  std::condition_variable cv_push, cv_pop;
  size_t capacity;
  std::atomic<bool> closed{false};
};

void* dl4j_ring_create(int64_t capacity) {
  Ring* r = new Ring();
  r->capacity = (size_t)capacity;
  return r;
}

// Blocks while full. Returns 0 on success, -1 if closed.
int32_t dl4j_ring_push(void* ring, void* buf, int64_t len) {
  Ring* r = (Ring*)ring;
  std::unique_lock<std::mutex> lk(r->m);
  r->cv_push.wait(lk, [&] { return r->q.size() < r->capacity || r->closed; });
  if (r->closed) return -1;
  r->q.push({buf, len});
  r->cv_pop.notify_one();
  return 0;
}

// Blocks while empty. Returns length, fills *buf; -1 if closed+drained.
int64_t dl4j_ring_pop(void* ring, void** buf) {
  Ring* r = (Ring*)ring;
  std::unique_lock<std::mutex> lk(r->m);
  r->cv_pop.wait(lk, [&] { return !r->q.empty() || r->closed; });
  if (r->q.empty()) return -1;
  auto item = r->q.front();
  r->q.pop();
  r->cv_push.notify_one();
  *buf = item.first;
  return item.second;
}

int64_t dl4j_ring_size(void* ring) {
  Ring* r = (Ring*)ring;
  std::lock_guard<std::mutex> lk(r->m);
  return (int64_t)r->q.size();
}

void dl4j_ring_close(void* ring) {
  Ring* r = (Ring*)ring;
  {
    std::lock_guard<std::mutex> lk(r->m);
    r->closed = true;
  }
  r->cv_pop.notify_all();
  r->cv_push.notify_all();
}

void dl4j_ring_destroy(void* ring) {
  Ring* r = (Ring*)ring;
  dl4j_ring_close(ring);
  while (!r->q.empty()) { free(r->q.front().first); r->q.pop(); }
  delete r;
}

// ---------------------------------------------------------------------------
// Host staging arena (≡ libnd4j MemoryWorkspace for host buffers): bump
// allocator with epoch reset — batch staging without per-batch malloc/free.
// ---------------------------------------------------------------------------
struct Arena {
  char* base;
  size_t capacity;
  std::atomic<size_t> offset{0};
};

void* dl4j_arena_create(int64_t capacity) {
  Arena* a = new Arena();
  a->base = (char*)malloc((size_t)capacity);
  a->capacity = (size_t)capacity;
  return a;
}

void* dl4j_arena_alloc(void* arena, int64_t size) {
  Arena* a = (Arena*)arena;
  size_t aligned = ((size_t)size + 63) & ~(size_t)63;
  size_t off = a->offset.fetch_add(aligned);
  if (off + aligned > a->capacity) return nullptr;  // caller falls back
  return a->base + off;
}

void dl4j_arena_reset(void* arena) { ((Arena*)arena)->offset = 0; }

int64_t dl4j_arena_used(void* arena) {
  return (int64_t)((Arena*)arena)->offset.load();
}

void dl4j_arena_destroy(void* arena) {
  Arena* a = (Arena*)arena;
  free(a->base);
  delete a;
}


// -- image ops (≡ datavec-data-image :: loader.NativeImageLoader — the
// reference resizes via native JavaCV/OpenCV; hand-rolled here, zero
// deps) -----------------------------------------------------------------
// Half-pixel-center bilinear (align_corners=False), u8 HWC -> f32 HWC in
// [0, 255]. Matches the numpy oracle in runtime/native_lib.py bit-for-bit
// in float32 (same clamp, same lerp order) — the strict-parity gate
// depends on that.
void dl4j_resize_bilinear_u8(const uint8_t* src, int64_t sh, int64_t sw,
                             int64_t c, float* dst, int64_t dh,
                             int64_t dw) {
  const float scale_y = (float)sh / (float)dh;
  const float scale_x = (float)sw / (float)dw;
  for (int64_t oy = 0; oy < dh; oy++) {
    float fy = ((float)oy + 0.5f) * scale_y - 0.5f;
    float fy0 = floorf(fy);
    float wy = fy - fy0;
    int64_t y0 = (int64_t)fy0;
    int64_t y0c = y0 < 0 ? 0 : (y0 >= sh ? sh - 1 : y0);
    int64_t y1 = y0 + 1;
    int64_t y1c = y1 < 0 ? 0 : (y1 >= sh ? sh - 1 : y1);
    for (int64_t ox = 0; ox < dw; ox++) {
      float fx = ((float)ox + 0.5f) * scale_x - 0.5f;
      float fx0 = floorf(fx);
      float wx = fx - fx0;
      int64_t x0 = (int64_t)fx0;
      int64_t x0c = x0 < 0 ? 0 : (x0 >= sw ? sw - 1 : x0);
      int64_t x1 = x0 + 1;
      int64_t x1c = x1 < 0 ? 0 : (x1 >= sw ? sw - 1 : x1);
      const uint8_t* r0 = src + (y0c * sw) * c;
      const uint8_t* r1 = src + (y1c * sw) * c;
      float* o = dst + (oy * dw + ox) * c;
      for (int64_t ch = 0; ch < c; ch++) {
        float v00 = (float)r0[x0c * c + ch];
        float v01 = (float)r0[x1c * c + ch];
        float v10 = (float)r1[x0c * c + ch];
        float v11 = (float)r1[x1c * c + ch];
        float top = v00 + (v01 - v00) * wx;
        float bot = v10 + (v11 - v10) * wx;
        o[ch] = top + (bot - top) * wy;
      }
    }
  }
}


}  // extern "C"
