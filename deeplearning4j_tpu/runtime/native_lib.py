"""ctypes bindings + on-demand build of the native runtime
(runtime/native/dl4j_native.cpp). Falls back to pure numpy when the
toolchain is unavailable — every caller checks `available()`.

ctypes releases the GIL during calls, so batch conversion in the native
path truly overlaps Python-side device dispatch (the reference gets the
same overlap from its javacpp worker threads).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "dl4j_native.cpp")
_SO = os.path.join(_HERE, "native", "libdl4j_native.so")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True)


def get_lib():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except Exception:
            _build_failed = True
            return None
        c = ctypes
        lib.dl4j_idx_read.restype = c.c_void_p
        lib.dl4j_idx_read.argtypes = [c.c_char_p, c.POINTER(c.c_int64),
                                      c.POINTER(c.c_int32),
                                      c.POINTER(c.c_int32)]
        lib.dl4j_free.argtypes = [c.c_void_p]
        lib.dl4j_u8_to_f32.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                       c.c_float, c.c_float]
        lib.dl4j_gather_batch_u8.argtypes = [c.c_void_p, c.c_int64,
                                             c.c_void_p, c.c_int64,
                                             c.c_void_p, c.c_float, c.c_float]
        lib.dl4j_one_hot.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                     c.c_int64, c.c_void_p]
        lib.dl4j_sub_channel_means.argtypes = [c.c_void_p, c.c_int64,
                                               c.c_int64, c.c_void_p]
        lib.dl4j_resize_bilinear_u8.argtypes = [
            c.c_void_p, c.c_int64, c.c_int64, c.c_int64,
            c.c_void_p, c.c_int64, c.c_int64]
        lib.dl4j_standardize.argtypes = [c.c_void_p, c.c_int64, c.c_int64,
                                         c.c_void_p, c.c_void_p]
        lib.dl4j_csv_dims.argtypes = [c.c_char_p, c.c_char, c.c_int32,
                                      c.POINTER(c.c_int64),
                                      c.POINTER(c.c_int64)]
        lib.dl4j_csv_parse.restype = c.c_int64
        lib.dl4j_csv_parse.argtypes = [c.c_char_p, c.c_char, c.c_int32,
                                       c.c_int64, c.c_int64, c.c_void_p]
        lib.dl4j_ring_create.restype = c.c_void_p
        lib.dl4j_ring_create.argtypes = [c.c_int64]
        lib.dl4j_ring_push.restype = c.c_int32
        lib.dl4j_ring_push.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
        lib.dl4j_ring_pop.restype = c.c_int64
        lib.dl4j_ring_pop.argtypes = [c.c_void_p, c.POINTER(c.c_void_p)]
        lib.dl4j_ring_size.restype = c.c_int64
        lib.dl4j_ring_size.argtypes = [c.c_void_p]
        lib.dl4j_ring_close.argtypes = [c.c_void_p]
        lib.dl4j_ring_destroy.argtypes = [c.c_void_p]
        lib.dl4j_arena_create.restype = c.c_void_p
        lib.dl4j_arena_create.argtypes = [c.c_int64]
        lib.dl4j_arena_alloc.restype = c.c_void_p
        lib.dl4j_arena_alloc.argtypes = [c.c_void_p, c.c_int64]
        lib.dl4j_arena_reset.argtypes = [c.c_void_p]
        lib.dl4j_arena_used.restype = c.c_int64
        lib.dl4j_arena_used.argtypes = [c.c_void_p]
        lib.dl4j_arena_destroy.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available():
    return get_lib() is not None


# -- numpy-level wrappers ------------------------------------------------
def idx_read(path):
    """Parse an (uncompressed) IDX file natively -> numpy array, or None."""
    lib = get_lib()
    if lib is None or path.endswith(".gz"):
        return None
    dims = (ctypes.c_int64 * 8)()
    ndim = ctypes.c_int32()
    dtype_code = ctypes.c_int32()
    ptr = lib.dl4j_idx_read(path.encode(), dims, ctypes.byref(ndim),
                            ctypes.byref(dtype_code))
    if not ptr:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
             13: np.float32, 14: np.float64}[dtype_code.value]
    n = int(np.prod(shape))
    buf = (ctypes.c_char * (n * np.dtype(dtype).itemsize)).from_address(ptr)
    arr = np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    lib.dl4j_free(ptr)
    return arr


def csv_to_floats(path_or_bytes, delimiter=",", skip_rows=0):
    """Parse an all-numeric CSV natively into a float32 (rows, cols) array
    (non-numeric/empty fields become NaN). Returns None when the native
    lib is unavailable — callers fall back to the Python csv module."""
    lib = get_lib()
    if lib is None:
        return None
    if isinstance(path_or_bytes, str) and os.path.exists(path_or_bytes):
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    elif isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        data = str(path_or_bytes).encode()
    data = data + b"\0"
    delim = delimiter.encode()[:1] or b","
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    lib.dl4j_csv_dims(data, delim, skip_rows,
                      ctypes.byref(rows), ctypes.byref(cols))
    r, c = rows.value, cols.value
    if r <= 0 or c <= 0:
        return np.empty((0, 0), np.float32)
    out = np.empty((r, c), np.float32)
    n = lib.dl4j_csv_parse(data, delim, skip_rows, r, c,
                           out.ctypes.data_as(ctypes.c_void_p))
    if n != r * c:
        return None  # inconsistent parse: let the caller use the slow path
    return out


def gather_batch_u8(archive, indices, scale=1.0 / 255.0, bias=0.0, out=None):
    """(N, ...)-uint8 archive + int64 indices -> (B, ...) float32 batch."""
    lib = get_lib()
    item_size = int(np.prod(archive.shape[1:]))
    idx = np.ascontiguousarray(indices, np.int64)
    b = len(idx)
    if out is None:
        out = np.empty((b,) + archive.shape[1:], np.float32)
    if lib is None:
        out[:] = archive[idx].astype(np.float32) * scale + bias
        return out
    lib.dl4j_gather_batch_u8(
        archive.ctypes.data_as(ctypes.c_void_p), item_size,
        idx.ctypes.data_as(ctypes.c_void_p), b,
        out.ctypes.data_as(ctypes.c_void_p), scale, bias)
    return out


def one_hot_u8(labels_u8, indices, n_classes, out=None):
    lib = get_lib()
    idx = np.ascontiguousarray(indices, np.int64)
    b = len(idx)
    if out is None:
        out = np.empty((b, n_classes), np.float32)
    if lib is None:
        out[:] = 0.0
        out[np.arange(b), labels_u8[idx].astype(np.int64)] = 1.0
        return out
    lib.dl4j_one_hot(labels_u8.ctypes.data_as(ctypes.c_void_p),
                     idx.ctypes.data_as(ctypes.c_void_p), b, n_classes,
                     out.ctypes.data_as(ctypes.c_void_p))
    return out


def standardize_inplace(data, mean, std):
    lib = get_lib()
    rows = data.shape[0]
    cols = int(np.prod(data.shape[1:]))
    if lib is None:
        flat = data.reshape(rows, cols)
        flat -= mean
        flat /= std
        return data
    lib.dl4j_standardize(data.ctypes.data_as(ctypes.c_void_p), rows, cols,
                         np.ascontiguousarray(mean, np.float32).ctypes
                         .data_as(ctypes.c_void_p),
                         np.ascontiguousarray(std, np.float32).ctypes
                         .data_as(ctypes.c_void_p))
    return data


def _resize_bilinear_oracle(img_u8, out_h, out_w):
    """numpy reference with EXACTLY the C kernel's math (half-pixel
    centers, clamped edges, float32 lerp order) — the parity gate and the
    no-toolchain fallback are the same function."""
    src = img_u8.astype(np.float32)
    sh, sw, c = src.shape
    scale_y = np.float32(sh) / np.float32(out_h)
    scale_x = np.float32(sw) / np.float32(out_w)
    fy = (np.arange(out_h, dtype=np.float32) + np.float32(0.5)) * scale_y \
        - np.float32(0.5)
    fx = (np.arange(out_w, dtype=np.float32) + np.float32(0.5)) * scale_x \
        - np.float32(0.5)
    y0 = np.floor(fy).astype(np.int64)
    x0 = np.floor(fx).astype(np.int64)
    wy = (fy - y0.astype(np.float32)).astype(np.float32)
    wx = (fx - x0.astype(np.float32)).astype(np.float32)
    y0c = np.clip(y0, 0, sh - 1)
    y1c = np.clip(y0 + 1, 0, sh - 1)
    x0c = np.clip(x0, 0, sw - 1)
    x1c = np.clip(x0 + 1, 0, sw - 1)
    v00 = src[y0c[:, None], x0c[None, :], :]
    v01 = src[y0c[:, None], x1c[None, :], :]
    v10 = src[y1c[:, None], x0c[None, :], :]
    v11 = src[y1c[:, None], x1c[None, :], :]
    wxb = wx[None, :, None]
    top = v00 + (v01 - v00) * wxb
    bot = v10 + (v11 - v10) * wxb
    return (top + (bot - top) * wy[:, None, None]).astype(np.float32)


def resize_bilinear_u8(img_u8, out_h, out_w):
    """u8 (H, W, C) -> f32 (out_h, out_w, C) in [0, 255]: the native
    kernel when available (strict-parity-gated against the numpy oracle
    once per process), the oracle otherwise — identical output either
    way."""
    img_u8 = np.ascontiguousarray(img_u8, np.uint8)
    if img_u8.ndim == 2:
        img_u8 = img_u8[:, :, None]
    lib = get_lib()
    if lib is None or not _resize_parity_ok():
        return _resize_bilinear_oracle(img_u8, out_h, out_w)
    sh, sw, c = img_u8.shape
    out = np.empty((int(out_h), int(out_w), c), np.float32)
    lib.dl4j_resize_bilinear_u8(
        img_u8.ctypes.data_as(ctypes.c_void_p), sh, sw, c,
        out.ctypes.data_as(ctypes.c_void_p), int(out_h), int(out_w))
    return out


_resize_parity = None


def _resize_parity_ok():
    """One-time gate: the native kernel must match the oracle on a fixed
    random probe (both up- and down-scale) or we never use it."""
    global _resize_parity
    if _resize_parity is not None:
        return _resize_parity
    lib = get_lib()
    if lib is None:
        _resize_parity = False
        return False
    rng = np.random.default_rng(0)
    probe = rng.integers(0, 256, size=(13, 17, 3), dtype=np.uint8)
    ok = True
    for oh, ow in ((7, 9), (29, 31)):
        want = _resize_bilinear_oracle(probe, oh, ow)
        got = np.empty((oh, ow, 3), np.float32)
        lib.dl4j_resize_bilinear_u8(
            probe.ctypes.data_as(ctypes.c_void_p), 13, 17, 3,
            got.ctypes.data_as(ctypes.c_void_p), oh, ow)
        if not np.allclose(got, want, atol=1e-3):
            ok = False
            break
    _resize_parity = ok
    return ok


class NativeArena:
    """Host staging arena (≡ MemoryWorkspace): bump-alloc + epoch reset."""

    def __init__(self, capacity_bytes):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native lib unavailable")
        self._lib = lib
        self._handle = lib.dl4j_arena_create(capacity_bytes)
        self.capacity = capacity_bytes

    def alloc_f32(self, shape):
        n = int(np.prod(shape))
        ptr = self._lib.dl4j_arena_alloc(self._handle, n * 4)
        if not ptr:
            return np.empty(shape, np.float32)  # arena full: heap fallback
        buf = (ctypes.c_float * n).from_address(ptr)
        return np.frombuffer(buf, np.float32).reshape(shape)

    def reset(self):
        self._lib.dl4j_arena_reset(self._handle)

    def used(self):
        return int(self._lib.dl4j_arena_used(self._handle))

    def close(self):
        if self._handle:
            self._lib.dl4j_arena_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
