"""Host pipeline: async dispatch + device staging prefetch.

BENCH.md's profile puts single-chip XLA fusions within ~1.5x of the HBM
bound, so the remaining throughput lever is the HOST side. Two host
pathologies starved the device in the pre-pipeline fit loops (the same
per-step host round-trips PAPERS.md's PyGraph analysis shows killing
CUDA-graph throughput):

1. **per-step blocking sync** — every fit loop did `float(loss)` each
   step, parking the host until the device finished. JAX's async
   dispatch lets the host run ahead, queueing step N+1 (and N+2, ...)
   while step N computes; one `float()` per step forfeits that. The fix
   is the *lazy score*: `_score` holds the device scalar and only
   `score()` (listeners, early stopping, user code) materializes it —
   numerics are bit-identical, only WHEN the host blocks changes. Sync
   cadence is therefore the consumer's cadence: a
   `ScoreIterationListener(10)` syncs every 10 steps, a listener-free
   `fit()` never syncs.

2. **synchronous input staging** — batch N+1's host→device conversion
   waited for step N's dispatch loop. `PrefetchIterator` moves
   pull + preprocess + device staging to a background thread with a
   bounded queue (double-buffered by default), so input prep overlaps
   device compute (the upstream DL4J `AsyncDataSetIterator` /
   `prefetchBuffer` idea, extended to stage all the way onto the
   device).

Staging is donation-safe by construction: every host array is copied
through `xla_owned_copy`, because on this backend `jnp.asarray(numpy)`
zero-copy ALIASES suitably-aligned numpy buffers and a donating jitted
step then frees memory numpy owns — free(): corrupted chunks / NaN
params / segfaults (root-caused in the resilience PR, 20/20 aliased on
fresh allocations, 0/20 through the misaligned-view copy).

Observability (`dl4j.pipeline.*`, zero-cost when monitoring is
disabled): `syncs` counts host-blocking materializations (the
regression guard: a listener-free fit must record 0 per-step syncs),
`host_blocked_ms` how long each blocked, `prefetch_depth` the staging
queue occupancy, `staged_batches` throughput of the staging thread.

`bench_pipeline.py` (repo root, CPU-runnable) measures the overlap win
against an IO-bound synthetic loader.
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring.state import STATE

__all__ = [
    "DEFAULT_PREFETCH", "PrefetchIterator", "StagedBatch",
    "StagedMultiBatch", "as_unaliasable", "blocking_float",
    "materialize_score", "maybe_prefetch", "stage_dataset",
    "stage_for_eval", "xla_owned_copy",
]

#: default staging queue depth (double buffer): batch N+1 stages while
#: step N computes. 0 disables prefetch globally.
DEFAULT_PREFETCH = int(os.environ.get("DL4J_PIPELINE_PREFETCH", "2"))


def as_unaliasable(host):
    """A bit-exact but deliberately MISALIGNED copy of `host` that
    jax's zero-copy eligibility check refuses — `device_put` /
    `jnp.asarray` / `make_array_from_callback` of this view always
    performs a REAL copy into XLA-allocated memory. The building block
    of `xla_owned_copy`; exported for the per-shard staging paths
    (multi-host placements go shard-by-shard through
    `make_array_from_callback`, which would otherwise alias each shard's
    numpy view exactly like a whole-array put)."""
    host = np.asarray(host)
    if host.nbytes == 0:
        return host
    raw = np.empty(host.nbytes + 1, np.uint8)
    view = raw[1:1 + host.nbytes].view(host.dtype).reshape(host.shape)
    view[...] = host
    return view


def xla_owned_copy(host, sharding=None):
    """A jax array GUARANTEED to own its buffer (bit-exact copy of
    `host`). On this jax CPU backend `jnp.asarray(numpy)` zero-copy
    aliases any suitably-aligned numpy buffer (measured 20/20 on fresh
    allocations); when a donating jitted step later consumes such an
    array, XLA frees/reuses memory numpy owns — heap corruption that
    surfaces as free(): corrupted chunks, NaN params, or segfaults a
    step or two after resume. Staging through a deliberately MISALIGNED
    view (`as_unaliasable`) makes the zero-copy eligibility check fail,
    forcing a real copy into XLA-allocated memory (verified 0/20
    aliased). Pass `sharding` to land the copy directly on an explicit
    placement."""
    view = as_unaliasable(host)
    if view.nbytes == 0:
        out = jnp.asarray(view)
        return out if sharding is None else jax.device_put(out, sharding)
    if sharding is None:
        return jnp.asarray(view)
    return jax.device_put(view, sharding)


# -- lazy score ------------------------------------------------------------
def record_sync(site, blocked_ms):
    """Account ONE host-blocking device sync: `dl4j.pipeline.syncs` +
    `host_blocked_ms` + flight-recorder attribution (the stall lands on
    the current step's record, so GET /steps phase coverage stays
    honest). Shared by `blocking_float` and the guardian's stacked
    verdict read — the zero-sync regression harness counts both through
    the same metric."""
    if not _mon.enabled():
        return
    reg = _mon.get_registry()
    reg.counter(_mon.PIPELINE_SYNCS, labels={"site": site},
                help="host-blocking device syncs (0/step when the "
                     "pipeline is healthy)").inc()
    reg.histogram(_mon.PIPELINE_HOST_BLOCKED_MS, labels={"site": site},
                  help="wall time the host spent blocked per sync") \
       .observe(blocked_ms)
    _mon.step_recorder().on_host_blocked(blocked_ms)


def blocking_float(value, site="score"):
    """float(device scalar), COUNTED: every call that actually blocks on
    the device lands on `dl4j.pipeline.syncs` (+ a host_blocked_ms
    observation), so a re-introduced per-step sync shows up in metrics
    and trips the tier-1 regression test."""
    if value is None:
        return None
    if isinstance(value, (float, int)):
        return float(value)
    if not STATE.enabled:
        return float(value)
    t0 = time.perf_counter()
    v = float(value)
    record_sync(site, (time.perf_counter() - t0) * 1e3)
    return v


def materialize_score(model, site="score"):
    """The one place `_score` turns host-side: floats a device-resident
    loss on demand and caches the float back, so N listeners reading the
    same iteration's score cost ONE sync."""
    s = model._score
    if s is None or isinstance(s, float):
        return s
    v = blocking_float(s, site=site)
    model._score = v
    return v


# -- staged batch containers ----------------------------------------------
class StagedBatch:
    """Device-resident DataSet stand-in: same read surface
    (features/labels/masks, numExamples) but every array is already an
    XLA-owned device buffer, so the fit paths' `jnp.asarray` is a no-op
    and the host never touches the bytes again. Deliberately NOT a
    DataSet subclass — DataSet.__init__ coerces to numpy, which would
    drag the arrays straight back to the host."""

    __slots__ = ("features", "labels", "featuresMask", "labelsMask",
                 "_host_finite")

    def __init__(self, features, labels, featuresMask=None,
                 labelsMask=None, host_finite=None):
        self.features = features
        self.labels = labels
        self.featuresMask = featuresMask
        self.labelsMask = labelsMask
        self._host_finite = host_finite

    def numExamples(self):
        return 0 if self.features is None else int(self.features.shape[0])


class StagedMultiBatch:
    """MultiDataSet counterpart of StagedBatch (list-of-arrays fields)."""

    __slots__ = ("features", "labels", "featuresMasks", "labelsMasks",
                 "_host_finite")

    def __init__(self, features, labels, featuresMasks=None,
                 labelsMasks=None, host_finite=None):
        self.features = features
        self.labels = labels
        self.featuresMasks = featuresMasks
        self.labelsMasks = labelsMasks
        self._host_finite = host_finite


class _EvalStaged:
    """Eval staging: features (what the forward pass consumes) go to the
    device; labels/masks stay HOST-side numpy — the evaluator reads them
    on the host, so staging them would just bounce the bytes
    host→device→host. Everything not staged proxies to the original."""

    __slots__ = ("_ds", "features", "featuresMask")

    def __init__(self, ds, features, featuresMask):
        self._ds = ds
        self.features = features
        self.featuresMask = featuresMask

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_ds"), name)


def _owned(a):
    if a is None:
        return None
    if isinstance(a, jax.Array):
        return a
    return xla_owned_copy(np.asarray(a))


def _host_floats_finite(arrays):
    """Finite check on HOST arrays (pre-staging). After staging the check
    would force a blocking device readback per batch — exactly the sync
    this pipeline removes — so FaultTolerantTrainer consumes this
    precomputed verdict instead."""
    for a in arrays:
        if a is None:
            continue
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating) \
                and not np.isfinite(arr).all():
            return False
    return True


def stage_dataset(ds, check_finite=False):
    """Stage one DataSet/MultiDataSet onto the device through XLA-owned
    copies. Runs on the prefetch worker thread, overlapping the NEXT
    step's H2D conversion with the current step's compute."""
    multi = isinstance(getattr(ds, "features", None), (list, tuple))
    if multi:
        arrays = list(ds.features) + list(ds.labels or [])
        finite = _host_floats_finite(arrays) if check_finite else None
        staged = StagedMultiBatch(
            [_owned(f) for f in ds.features],
            None if ds.labels is None else [_owned(l) for l in ds.labels],
            None if ds.featuresMasks is None
            else [_owned(m) for m in ds.featuresMasks],
            None if ds.labelsMasks is None
            else [_owned(m) for m in ds.labelsMasks],
            host_finite=finite)
    else:
        finite = (_host_floats_finite([ds.features, ds.labels])
                  if check_finite else None)
        staged = StagedBatch(_owned(ds.features), _owned(ds.labels),
                             _owned(getattr(ds, "featuresMask", None)),
                             _owned(getattr(ds, "labelsMask", None)),
                             host_finite=finite)
    if STATE.enabled:
        _mon.get_registry().counter(
            _mon.PIPELINE_STAGED_BATCHES,
            help="batches staged to device by the prefetch worker").inc()
    return staged


def stage_for_eval(ds):
    """Eval-loop staging: device-stage features (+features mask) only."""
    feats = getattr(ds, "features", None)
    if isinstance(feats, (list, tuple)):
        staged = [_owned(f) for f in feats]
    else:
        staged = _owned(feats)
    fm = getattr(ds, "featuresMask", None)
    return _EvalStaged(ds, staged, _owned(fm))


# -- the prefetcher --------------------------------------------------------
class PrefetchIterator:
    """Background-thread prefetch with optional device staging.

    Wraps either a DataSetIterator (hasNext/next protocol) or any plain
    iterable. The worker pulls `base`, applies `stage` (e.g.
    `stage_dataset` → XLA-owned device arrays), and feeds a bounded
    queue of depth `depth`; the consumer side exposes the standard
    hasNext/next/reset surface plus python iteration.

    Failure semantics (the two classic async-iterator bugs, fixed by
    construction):
    - an exception in the worker — `base.next()` raising, staging
      failing — is CAPTURED and re-raised in the consumer with the
      original traceback; it can never masquerade as a clean
      end-of-stream and silently truncate the epoch;
    - the consumer polls the queue with a timeout and checks worker
      liveness, so a worker that dies without posting a result surfaces
      as an error instead of deadlocking `hasNext` forever.
    """

    _EMPTY = object()    # nothing peeked yet
    _EOS = object()      # worker saw clean end-of-stream
    _FAILED = object()   # worker captured an exception (see self._error)
    _POLL_S = 0.25       # consumer liveness-poll interval

    def __init__(self, base, depth=2, stage=None):
        self._base = base
        self._depth = max(1, int(depth))
        self._stage = stage
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = None
        self._peek = self._EMPTY
        self._error = None

    # -- worker side -----------------------------------------------------
    def _offer(self, q, stop, item):
        """put() that a reset()/close() can always interrupt — a plain
        blocking put on a full queue with a gone consumer would leak the
        worker thread forever."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self, q, stop):
        # q/stop are THIS generation's objects, bound at thread start: a
        # straggler worker from before a reset() can never touch the
        # fresh queue or see the fresh (cleared) stop event
        try:
            base = self._base
            if hasattr(base, "hasNext") and hasattr(base, "next"):
                while not stop.is_set() and base.hasNext():
                    item = base.next()
                    if self._stage is not None:
                        item = self._stage(item)
                    if not self._offer(q, stop, item):
                        return
            else:
                for item in iter(base):
                    if stop.is_set():
                        return
                    if self._stage is not None:
                        item = self._stage(item)
                    if not self._offer(q, stop, item):
                        return
        except BaseException as e:  # noqa: BLE001 — delivered to consumer
            self._error = e
            self._offer(q, stop, self._FAILED)
            return
        self._offer(q, stop, self._EOS)

    # -- consumer side ---------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, args=(self._queue, self._stop),
                daemon=True, name="dl4j-pipeline-prefetch")
            self._thread.start()

    def _get_item(self):
        self._ensure_thread()
        while True:
            try:
                item = self._queue.get(timeout=self._POLL_S)
            except _queue.Empty:
                t = self._thread
                if t is not None and t.is_alive():
                    continue
                # worker is gone: drain once more to close the race
                # where it posted between our get timing out and the
                # liveness check
                try:
                    item = self._queue.get_nowait()
                except _queue.Empty:
                    if self._error is not None:
                        raise self._error
                    raise RuntimeError(
                        "prefetch worker died without delivering a batch, "
                        "an error, or end-of-stream")
            if STATE.enabled:
                _mon.get_registry().gauge(
                    _mon.PIPELINE_PREFETCH_DEPTH,
                    help="staged batches waiting in the prefetch queue "
                         "(0 = device waiting on the loader)") \
                    .set(self._queue.qsize())
            return item

    def hasNext(self):
        if self._peek is self._EMPTY:
            self._peek = self._get_item()
        if self._peek is self._FAILED:
            # _peek stays FAILED: every subsequent hasNext/next re-raises
            # instead of pretending the stream ended cleanly
            raise self._error
        return self._peek is not self._EOS

    def next(self, num=None):
        if not self.hasNext():
            raise StopIteration("DataSetIterator exhausted; call reset()")
        item, self._peek = self._peek, self._EMPTY
        return item

    def failed(self):
        """True once the worker has died on an error: hasNext/next
        re-raise until reset() or resume_after_error() revives the
        stream."""
        return self._peek is self._FAILED

    def resume_after_error(self):
        """Clear a sticky worker failure and prefetch on from the base's
        CURRENT position (the failed pull's batch is lost, exactly as
        with a raw iterator whose next() raised mid-pull) — this is how
        skip-and-count consumers (FaultTolerantTrainer) keep their
        count-one-error-and-continue semantics with prefetch on. No-op
        unless in the failed state."""
        if self._peek is not self._FAILED:
            return
        self._shutdown_worker()
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._peek = self._EMPTY
        self._error = None

    # -- lifecycle -------------------------------------------------------
    def _shutdown_worker(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            deadline = time.monotonic() + 10.0
            while t.is_alive() and time.monotonic() < deadline:
                try:     # unblock a worker stuck in _offer on a full queue
                    self._queue.get_nowait()
                except _queue.Empty:
                    time.sleep(0.002)
            t.join(timeout=5)
        self._thread = None

    def reset(self):
        self._shutdown_worker()
        # fresh generation: new stop event + queue, so the (joined) old
        # worker's objects are dead ends even if it somehow lingered
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._peek = self._EMPTY
        self._error = None
        if hasattr(self._base, "reset"):
            self._base.reset()

    def close(self):
        """Stop the worker without resetting the base (for finally:
        blocks around a fit/eval that may exit early)."""
        self._shutdown_worker()

    # -- protocol parity -------------------------------------------------
    def resetSupported(self):
        sup = getattr(self._base, "resetSupported", None)
        return hasattr(self._base, "reset") if sup is None else sup()

    def asyncSupported(self):
        return False    # already async; double-wrapping buys nothing

    def batch(self):
        return self._base.batch()

    def numExamples(self):
        return self._base.numExamples()

    def totalOutcomes(self):
        return self._base.totalOutcomes()

    def inputColumns(self):
        return self._base.inputColumns()

    def setPreProcessor(self, pp):
        self._base.setPreProcessor(pp)

    def getPreProcessor(self):
        getpp = getattr(self._base, "getPreProcessor", None)
        return None if getpp is None else getpp()

    def __iter__(self):
        if self.resetSupported():
            self.reset()
        return self

    def __next__(self):
        if not self.hasNext():
            raise StopIteration
        return self.next()


def maybe_prefetch(data, depth=None, stage=None):
    """(iterator, prefetcher-or-None): wrap `data` in a staging
    prefetcher when it opts in (`asyncSupported()`) and `depth` > 0.
    The second element is the caller's close() handle (None when no
    wrapping happened). Already-wrapped iterators pass through."""
    depth = DEFAULT_PREFETCH if depth is None else int(depth)
    if depth <= 0 or isinstance(data, PrefetchIterator):
        return data, None
    sup = getattr(data, "asyncSupported", None)
    if sup is None or not sup():
        return data, None
    pf = PrefetchIterator(data, depth=depth,
                          stage=stage_dataset if stage is None else stage)
    return pf, pf
