"""Memory workspaces (≡ libnd4j MemoryWorkspace / nd4j WorkspaceConfiguration).

The reference's workspaces exist to reuse device scratch between iterations
without GC pressure. On TPU/XLA that job is done by (a) buffer donation —
our train steps donate params/opt-state/bn-state so XLA updates in place —
and (b) XLA's own arena allocation inside one executable. What remains
host-side is batch staging, covered by runtime.native_lib.NativeArena.

This module keeps the reference's API shape so user code ports cleanly:
`with Nd4jWorkspace("WS"): ...` scopes a host staging arena, and
WorkspaceConfiguration maps its knobs onto arena sizing.
"""
from __future__ import annotations

import numpy as np


class WorkspaceConfiguration:
    def __init__(self, initialSize=64 << 20, policyAllocation="strict",
                 policyLearning="first_loop"):
        self.initialSize = int(initialSize)
        self.policyAllocation = policyAllocation
        self.policyLearning = policyLearning


class Nd4jWorkspace:
    """Host staging workspace: float32 scratch from a native bump arena,
    reset on scope exit (device side: XLA donation — nothing to do)."""

    def __init__(self, id="WS", configuration=None):
        from deeplearning4j_tpu.runtime.native_lib import NativeArena, available
        self.id = id
        conf = configuration or WorkspaceConfiguration()
        self._arena = None
        if available():
            try:
                self._arena = NativeArena(conf.initialSize)
            except RuntimeError:
                self._arena = None

    def alloc(self, shape, dtype=np.float32):
        if self._arena is not None and np.dtype(dtype) == np.float32:
            return self._arena.alloc_f32(shape)
        return np.empty(shape, dtype)

    def reset(self):
        if self._arena is not None:
            self._arena.reset()

    def bytes_used(self):
        return self._arena.used() if self._arena is not None else 0

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.reset()
        return False

    def close(self):
        if self._arena is not None:
            self._arena.close()
            self._arena = None
