"""Transfer learning (≡ deeplearning4j-nn :: transferlearning.TransferLearning,
FineTuneConfiguration, TransferLearningHelper).

The reference edits a trained MultiLayerNetwork/ComputationGraph in place:
freeze a feature-extractor prefix (FrozenLayer wrappers), swap/replace output
layers, and fine-tune the remainder. Here the same surface produces a NEW
network whose retained layers receive copies of the trained parameter
arrays (copies, not references: both nets' jitted train steps DONATE their
param buffers, so sharing would let one net delete the other's arrays),
and "frozen" is expressed the TPU-native way:
frozen layers get a NoOp updater partition in the single jitted train step
(optax.multi_transform), so XLA still fuses one step executable and the
frozen subtree simply receives zero updates. Frozen layers also run in
inference mode (no dropout, batch-norm running stats pinned), matching the
reference's FrozenLayer semantics.
"""
from __future__ import annotations

import copy

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
from deeplearning4j_tpu.nn.updaters import NoOp


class FineTuneConfiguration:
    """Hyperparameter overrides applied to every non-frozen layer
    (≡ transferlearning.FineTuneConfiguration)."""

    def __init__(self, overrides, seed=None):
        self.overrides = dict(overrides)
        self.seed = seed

    class Builder:
        def __init__(self):
            self._overrides = {}
            self._seed = None

        def seed(self, s):
            self._seed = int(s)
            return self

        def updater(self, u):
            self._overrides["updater"] = u
            return self

        def activation(self, a):
            self._overrides["activation"] = a
            return self

        def weightInit(self, w):
            self._overrides["weightInit"] = w
            return self

        def biasInit(self, b):
            self._overrides["biasInit"] = float(b)
            return self

        def l1(self, v):
            self._overrides["l1"] = float(v)
            return self

        def l2(self, v):
            self._overrides["l2"] = float(v)
            return self

        def weightDecay(self, v):
            self._overrides["weightDecay"] = float(v)
            return self

        def dropOut(self, p):
            self._overrides["dropOut"] = float(p)
            return self

        def gradientNormalization(self, gn):
            self._overrides["gradientNormalization"] = gn
            return self

        def gradientNormalizationThreshold(self, t):
            self._overrides["gradientNormalizationThreshold"] = float(t)
            return self

        def optimizationAlgo(self, algo):  # parity no-op (XLA)
            return self

        def build(self):
            return FineTuneConfiguration(self._overrides, self._seed)


def _reshare_global_updater(layer, old_defaults, new_defaults):
    """Deepcopy broke updater object identity, which the optimizer uses to
    partition per-layer updaters: restore sharing when the layer's updater
    was just the old global one (same type + hyperparameters)."""
    old_updater = old_defaults.get("updater")
    if (old_updater is not None and layer.updater is not None
            and type(layer.updater) is type(old_updater)
            and vars(layer.updater) == vars(old_updater)):
        layer.updater = new_defaults["updater"]


def _freeze_layer_conf(layer):
    """Mark a deep-copied layer conf frozen: NoOp updates, no regularization,
    inference-mode forward."""
    layer.frozen = True
    layer.updater = NoOp()        # its own instance → per-layer optax label
    layer.l1 = 0.0
    layer.l2 = 0.0
    layer.weightDecay = 0.0
    layer.dropOut = 0.0
    return layer


class TransferLearning:
    """Namespace matching the reference: TransferLearning.Builder for
    MultiLayerNetwork, TransferLearning.GraphBuilder for ComputationGraph."""

    class Builder:
        def __init__(self, net):
            if net._params is None:
                raise ValueError("TransferLearning requires an initialized "
                                 "network (call init() / load a model first)")
            self._net = net
            self._conf = net.conf
            self._fine_tune = None
            self._frozen_till = -1           # freeze layers [0.._frozen_till]
            self._nout_replace = {}          # idx -> (nOut, wInit, wInitNext)
            self._n_keep = len(net.layers)   # layers retained from the source
            self._added = []                 # appended layer confs
            self._input_type = net.conf.input_type

        def fineTuneConfiguration(self, ftc):
            self._fine_tune = ftc
            return self

        def setFeatureExtractor(self, layer_idx):
            """Freeze layers [0..layer_idx] inclusive (≡ reference)."""
            self._frozen_till = int(layer_idx)
            return self

        def nOutReplace(self, layer_idx, n_out, weight_init=None,
                        weight_init_next=None):
            """Change layer layer_idx's nOut and re-initialize it (and the
            nIn of the next parametric layer) — ≡ reference nOutReplace."""
            self._nout_replace[int(layer_idx)] = (
                int(n_out), weight_init, weight_init_next)
            return self

        def removeOutputLayer(self):
            return self.removeLayersFromOutput(1)

        def removeLayersFromOutput(self, n):
            if self._added:
                raise ValueError("remove*() must precede addLayer()")
            self._n_keep = max(0, self._n_keep - int(n))
            return self

        def addLayer(self, layer_conf):
            from deeplearning4j_tpu.nn.conf import layers as L
            if isinstance(layer_conf, L._Builder):
                layer_conf = layer_conf.build()
            self._added.append(layer_conf)
            return self

        def setInputType(self, input_type):
            self._input_type = input_type
            return self

        def build(self):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            src = self._net
            old_defaults = src.conf.defaults
            n_keep = self._n_keep
            kept = [copy.deepcopy(l) for l in src.layers[:n_keep]]
            added = [copy.deepcopy(l) for l in self._added]

            # fine-tune overrides: new defaults + direct application to
            # retained non-frozen layers (their fields were already filled
            # from the OLD defaults at the original build)
            defaults = dict(old_defaults)
            ft = self._fine_tune.overrides if self._fine_tune else {}
            defaults.update(ft)
            seed = (self._fine_tune.seed
                    if self._fine_tune and self._fine_tune.seed is not None
                    else src.conf.seed)

            reinit = set()   # layer indices whose params are re-initialized
            for idx, (n_out, w_init, w_init_next) in self._nout_replace.items():
                if idx >= n_keep:
                    raise ValueError(f"nOutReplace({idx}): layer was removed")
                kept[idx].nOut = n_out
                if w_init is not None:
                    kept[idx].weightInit = w_init
                reinit.add(idx)
                # propagate the width change: layers WITHOUT an nIn attr
                # (BatchNormalization & co) are width-transparent but size
                # their params from the input — clear the size initialize()
                # pinned into the conf and re-init them; stop at the next
                # nIn-owning layer, whose nIn re-infers
                for j in range(idx + 1, n_keep):
                    lj = kept[j]
                    if getattr(lj, "nIn", None) is not None:
                        lj.nIn = None
                        if w_init_next is not None:
                            lj.weightInit = w_init_next
                        reinit.add(j)
                        break
                    if hasattr(lj, "nOut"):
                        lj.nOut = None
                        reinit.add(j)

            for i, layer in enumerate(kept):
                if i <= self._frozen_till:
                    _freeze_layer_conf(layer)
                    continue
                _reshare_global_updater(layer, old_defaults, defaults)
                for field, value in ft.items():
                    setattr(layer, field, value)

            new_layers = kept + added
            preprocessors = {i: pp for i, pp in src.conf.preprocessors.items()
                             if i < n_keep}
            conf = MultiLayerConfiguration(
                defaults, new_layers, self._input_type, preprocessors,
                src.conf.backprop_type, src.conf.tbptt_fwd_length,
                src.conf.tbptt_back_length, src.conf.data_type, seed)

            dst = MultiLayerNetwork(conf).init()
            # copy trained arrays for retained, shape-compatible layers
            # (copies: donated train-step buffers must not be shared)
            for i in range(n_keep):
                key = str(i)
                if i in reinit or key not in src._params:
                    continue
                if key in dst._params and all(
                        src._params[key][n].shape == dst._params[key][n].shape
                        for n in dst._params[key]):
                    dst._params[key] = {k: jnp.copy(v)
                                        for k, v in src._params[key].items()}
                if key in src._state and key in dst._state and all(
                        src._state[key][n].shape == dst._state[key][n].shape
                        for n in dst._state[key]):
                    dst._state[key] = {k: jnp.copy(v)
                                       for k, v in src._state[key].items()}
            dst._build_optimizer()
            return dst

    class GraphBuilder:
        """Transfer learning over ComputationGraph (by vertex name)."""

        def __init__(self, graph):
            if graph._params is None:
                raise ValueError("TransferLearning requires an initialized "
                                 "ComputationGraph")
            self._graph = graph
            self._fine_tune = None
            self._frozen_till = None          # freeze up to + incl this vertex
            self._nout_replace = {}           # name -> (nOut, wInit, wInitNext)
            self._removed = set()
            self._added = []                  # (name, layer_conf, inputs)
            self._outputs = None

        def fineTuneConfiguration(self, ftc):
            self._fine_tune = ftc
            return self

        def setFeatureExtractor(self, *vertex_names):
            self._frozen_till = set(vertex_names)
            return self

        def nOutReplace(self, name, n_out, weight_init=None,
                        weight_init_next=None):
            self._nout_replace[name] = (int(n_out), weight_init,
                                        weight_init_next)
            return self

        def removeVertexAndConnections(self, name):
            """Remove the vertex and strip every reference to it from
            retained downstream nodes (≡ reference semantics: downstream
            consumers must be rewired explicitly via addLayer/addVertex)."""
            self._removed.add(name)
            return self

        def removeVertexKeepConnections(self, name):
            """Remove the vertex but splice its inputs into its consumers
            (downstream nodes now read directly from its parents)."""
            self._rewired = getattr(self, "_rewired", set())
            self._rewired.add(name)
            return self

        def addLayer(self, name, layer_conf, *inputs):
            from deeplearning4j_tpu.nn.conf import layers as L
            if isinstance(layer_conf, L._Builder):
                layer_conf = layer_conf.build()
            if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
                inputs = tuple(inputs[0])
            self._added.append((name, layer_conf, list(inputs)))
            return self

        def setOutputs(self, *names):
            if len(names) == 1 and isinstance(names[0], (list, tuple)):
                names = names[0]
            self._outputs = list(names)
            return self

        def build(self):
            from deeplearning4j_tpu.nn.conf.graph_builder import (
                ComputationGraphConfiguration, GraphNode)
            from deeplearning4j_tpu.nn.graph import ComputationGraph

            src = self._graph
            sconf = src.conf
            ft = self._fine_tune.overrides if self._fine_tune else {}
            defaults = dict(sconf.defaults)
            defaults.update(ft)

            # frozen set: every ancestor of (and including) the named vertices
            frozen = set()
            if self._frozen_till:
                def mark(name):
                    if name in frozen or name not in sconf.nodes:
                        return
                    frozen.add(name)
                    for p in sconf.nodes[name].inputs:
                        mark(p)
                for name in self._frozen_till:
                    mark(name)

            rewired = getattr(self, "_rewired", set())

            def resolve_inputs(parents):
                """Strip removed references; splice through rewired ones."""
                out = []
                for p in parents:
                    if p in self._removed:
                        continue
                    if p in rewired:
                        out.extend(resolve_inputs(sconf.nodes[p].inputs))
                    else:
                        out.append(p)
                return out

            nodes = {}
            reinit = set()
            for name in sconf.topo_order:
                if name in self._removed or name in rewired:
                    continue
                n = sconf.nodes[name]
                ref = copy.deepcopy(n.ref)
                if n.kind == "layer":
                    if name in self._nout_replace:
                        n_out, w_init, _ = self._nout_replace[name]
                        ref.nOut = n_out
                        if w_init is not None:
                            ref.weightInit = w_init
                        reinit.add(name)
                    # a consumer's input dim changes if a replaced vertex is
                    # reachable through width-transparent paths: vertices
                    # (merge/elementwise forward dims without parameters) and
                    # nIn-less layers (BatchNormalization & co size params
                    # from the input but don't change the width)
                    def replaced_ancestors(node_name, _seen=None):
                        seen = set() if _seen is None else _seen
                        found = []
                        for p in sconf.nodes[node_name].inputs:
                            if p in seen:
                                continue
                            seen.add(p)
                            pn = sconf.nodes[p]
                            if p in self._nout_replace:
                                found.append(p)
                            elif (pn.kind == "vertex"
                                  or getattr(pn.ref, "nIn", None) is None):
                                found.extend(replaced_ancestors(p, seen))
                        return found

                    replaced_parents = replaced_ancestors(name)
                    if replaced_parents:
                        if getattr(ref, "nIn", None) is not None:
                            ref.nIn = None
                            # weight_init_next from THIS node's ancestor
                            w_next = self._nout_replace[replaced_parents[0]][2]
                            if w_next is not None:
                                ref.weightInit = w_next
                            reinit.add(name)
                        elif hasattr(ref, "nOut") and \
                                name not in self._nout_replace:
                            # width-transparent but parametric: re-infer size
                            ref.nOut = None
                            reinit.add(name)
                    if name in frozen:
                        _freeze_layer_conf(ref)
                    else:
                        _reshare_global_updater(ref, sconf.defaults, defaults)
                        for field, value in ft.items():
                            setattr(ref, field, value)
                node = GraphNode(name, n.kind, ref,
                                 resolve_inputs(n.inputs))
                node.preprocessor = copy.deepcopy(n.preprocessor)
                nodes[name] = node
            for name, layer, inputs in self._added:
                layer.name = name
                nodes[name] = GraphNode(name, "layer", layer, list(inputs))

            outputs = self._outputs or [o for o in sconf.output_names
                                        if o not in self._removed
                                        and o not in rewired]
            if not outputs:
                raise ValueError("All outputs were removed; call "
                                 "setOutputs(...) with the new output names")
            seed = (self._fine_tune.seed
                    if self._fine_tune and self._fine_tune.seed is not None
                    else sconf.seed)
            conf = ComputationGraphConfiguration(
                defaults, nodes, sconf.input_names, outputs,
                list(sconf.input_types), sconf.backprop_type,
                sconf.tbptt_fwd_length, sconf.tbptt_back_length,
                sconf.data_type, seed)
            dst = ComputationGraph(conf).init()
            # copies, not references: both nets' train steps donate buffers
            for name, p in src._params.items():
                if name in reinit or name not in dst._params:
                    continue
                if all(p[k].shape == dst._params[name][k].shape
                       for k in dst._params[name]):
                    dst._params[name] = {k: jnp.copy(v) for k, v in p.items()}
                if name in src._state and name in dst._state and all(
                        src._state[name][k].shape == dst._state[name][k].shape
                        for k in dst._state[name]):
                    dst._state[name] = {k: jnp.copy(v)
                                        for k, v in src._state[name].items()}
            dst._build_optimizer()
            return dst


class TransferLearningHelper:
    """≡ transferlearning.TransferLearningHelper: featurize a dataset at the
    frozen boundary once, then train only the unfrozen tail on the cached
    features (saves recomputing the frozen subtree every epoch)."""

    def __init__(self, net, frozen_till=None):
        self._net = net
        if frozen_till is None:
            frozen = [i for i, l in enumerate(net.layers)
                      if getattr(l, "frozen", False)]
            if not frozen:
                raise ValueError("Network has no frozen layers; pass "
                                 "frozen_till explicitly")
            frozen_till = max(frozen)
        self._boundary = int(frozen_till)
        self._sub = self._build_unfrozen()

    def _build_unfrozen(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net, b = self._net, self._boundary
        tail = [copy.deepcopy(l) for l in net.layers[b + 1:]]
        for layer in tail:
            layer.frozen = False
        preprocessors = {i - (b + 1): pp
                         for i, pp in net.conf.preprocessors.items()
                         if i > b}
        conf = MultiLayerConfiguration(
            dict(net.conf.defaults), tail, net.conf.input_types[b + 1],
            preprocessors, net.conf.backprop_type, net.conf.tbptt_fwd_length,
            net.conf.tbptt_back_length, net.conf.data_type, net.conf.seed)
        sub = MultiLayerNetwork(conf).init()
        for i in range(b + 1, len(net.layers)):
            key, sub_key = str(i), str(i - (b + 1))
            if key in net._params:
                sub._params[sub_key] = {k: jnp.copy(v)
                                        for k, v in net._params[key].items()}
            if key in net._state:
                sub._state[sub_key] = {k: jnp.copy(v)
                                       for k, v in net._state[key].items()}
        sub._build_optimizer()
        return sub

    def featurize(self, dataset):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        feats = self._net.activateSelectedLayers(
            0, self._boundary, dataset.features)
        return DataSet(feats.numpy(), dataset.labels,
                       dataset.featuresMask, dataset.labelsMask)

    def fitFeaturized(self, dataset_or_iter):
        self._sub.fit(dataset_or_iter)
        self._write_back()
        return self

    def outputFromFeaturized(self, features):
        return self._sub.output(features)

    def unfrozenMLN(self):
        return self._sub

    def _write_back(self):
        b = self._boundary
        for i in range(b + 1, len(self._net.layers)):
            key, sub_key = str(i), str(i - (b + 1))
            if sub_key in self._sub._params:
                self._net._params[key] = {
                    k: jnp.copy(v)
                    for k, v in self._sub._params[sub_key].items()}
            if sub_key in self._sub._state:
                self._net._state[key] = {
                    k: jnp.copy(v)
                    for k, v in self._sub._state[sub_key].items()}
