"""Transfer learning (≡ deeplearning4j-nn :: transferlearning)."""
from deeplearning4j_tpu.transfer.transfer_learning import (  # noqa: F401
    FineTuneConfiguration, TransferLearning, TransferLearningHelper)
