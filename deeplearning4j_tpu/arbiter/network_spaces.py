"""Declarative network hyperparameter spaces (≡ arbiter-deeplearning4j ::
MultiLayerSpace / ComputationGraphSpace / layers.DenseLayerSpace etc. /
adapter.ParameterSpaceAdapter).

A `LayerSpace(LayerCls, **kw)` holds per-field ParameterSpaces; a
`MultiLayerSpace` composes them plus global spaces (updater, l2, ...)
into ONE flat leaf dict the existing candidate generators already
understand, and compiles a sampled candidate into a real
MultiLayerConfiguration through the normal builder DSL — so a search
runs end-to-end through LocalOptimizationRunner with NO hand-written
model_builder (the round-3 gap: generic spaces existed, the declarative
network surface didn't).
"""
from __future__ import annotations

import copy

import numpy as np

from deeplearning4j_tpu.arbiter.spaces import ParameterSpace


def _resolve(v, cand, key):
    """Fixed value straight through; ParameterSpace leaves read their
    sampled value out of the candidate dict."""
    return cand[key] if isinstance(v, ParameterSpace) else v


class UpdaterSpace(ParameterSpace):
    """≡ arbiter :: AdamSpace / SgdSpace / NesterovsSpace — an updater
    whose learning rate is itself a space. Samples/grids the LR; the
    compiled config gets `cls(lr)`."""

    def __init__(self, updater_cls, learningRate):
        self.updater_cls = updater_cls
        self.lr = learningRate

    def sample(self, rng):
        return (self.lr.sample(rng)
                if isinstance(self.lr, ParameterSpace) else self.lr)

    def grid(self, n):
        return (self.lr.grid(n)
                if isinstance(self.lr, ParameterSpace) else [self.lr])

    def build(self, lr):
        return self.updater_cls(lr)


def AdamSpace(learningRate):
    from deeplearning4j_tpu.nn.updaters import Adam
    return UpdaterSpace(Adam, learningRate)


def SgdSpace(learningRate):
    from deeplearning4j_tpu.nn.updaters import Sgd
    return UpdaterSpace(Sgd, learningRate)


def NesterovsSpace(learningRate):
    from deeplearning4j_tpu.nn.updaters import Nesterovs
    return UpdaterSpace(Nesterovs, learningRate)


class LayerSpace:
    """≡ arbiter layers.*LayerSpace, generically: any constructor kwarg
    of any layer config class may be a ParameterSpace."""

    def __init__(self, layer_cls, **kw):
        self.layer_cls = layer_cls
        self.kw = kw

    def leaves(self, prefix):
        return {f"{prefix}.{k}": v for k, v in self.kw.items()
                if isinstance(v, ParameterSpace)}

    def build(self, cand, prefix):
        kw = {k: _resolve(v, cand, f"{prefix}.{k}")
              for k, v in self.kw.items()}
        return self.layer_cls(**kw)


class MultiLayerSpace:
    """≡ arbiter-deeplearning4j :: MultiLayerSpace."""

    def __init__(self, global_spaces, layer_specs, input_type, seed):
        self._globals = global_spaces      # {field: value|space}
        self._layers = layer_specs         # [(LayerSpace, repeat)]
        self._input_type = input_type
        self._seed = seed

    class Builder:
        def __init__(self):
            self._globals = {}
            self._layers = []
            self._input_type = None
            self._seed = 12345

        def seed(self, s):
            self._seed = int(s)
            return self

        def updater(self, u):
            self._globals["updater"] = u
            return self

        def weightInit(self, w):
            self._globals["weightInit"] = w
            return self

        def activation(self, a):
            self._globals["activation"] = a
            return self

        def l1(self, v):
            self._globals["l1"] = v
            return self

        def l2(self, v):
            self._globals["l2"] = v
            return self

        def dropOut(self, p):
            self._globals["dropOut"] = p
            return self

        def addLayer(self, layer_space, repeat=1):
            """repeat may be an IntegerParameterSpace (≡ the reference's
            `numLayers` arg) — every copy shares the SAME sampled
            hyperparameters, as in the reference."""
            self._layers.append((layer_space, repeat))
            return self

        def setInputType(self, t):
            self._input_type = t
            return self

        def build(self):
            if not self._layers:
                raise ValueError("MultiLayerSpace: addLayer() at least one "
                                 "layer space")
            return MultiLayerSpace(self._globals, list(self._layers),
                                   self._input_type, self._seed)

    # -- ParameterSpace protocol over the whole network ------------------
    def collectLeaves(self):
        """Flat {name: ParameterSpace} for the candidate generators."""
        leaves = {}
        for k, v in self._globals.items():
            if isinstance(v, ParameterSpace):
                leaves[f"global.{k}"] = v
        for i, (ls, repeat) in enumerate(self._layers):
            leaves.update(ls.leaves(f"layer{i}"))
            if isinstance(repeat, ParameterSpace):
                leaves[f"layer{i}.repeat"] = repeat
        return leaves

    def getValue(self, cand):
        """candidate dict → MultiLayerConfiguration (via the real DSL)."""
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        b = NeuralNetConfiguration.Builder().seed(self._seed)
        for k, v in self._globals.items():
            val = _resolve(v, cand, f"global.{k}")
            if isinstance(v, UpdaterSpace):
                val = v.build(val)
            getattr(b, k)(val)
        lb = b.list()
        for i, (ls, repeat) in enumerate(self._layers):
            n = int(_resolve(repeat, cand, f"layer{i}.repeat"))
            for _ in range(max(1, n)):
                # raw confs are deep-copied: conf building MUTATES layers
                # (nIn inference, apply_defaults) and one candidate's
                # inferred shapes must never leak into the next
                lb.layer(ls.build(cand, f"layer{i}")
                         if isinstance(ls, LayerSpace)
                         else copy.deepcopy(ls))
        if self._input_type is not None:
            lb.setInputType(self._input_type)
        return lb.build()

    def model_builder(self):
        """Drop-in `model_builder` for LocalOptimizationRunner: candidate
        → initialized MultiLayerNetwork."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        def build(cand):
            return MultiLayerNetwork(self.getValue(cand)).init()

        return build

    def randomCandidate(self, seed=0):
        rng = np.random.default_rng(seed)
        return {k: v.sample(rng) for k, v in self.collectLeaves().items()}


class ComputationGraphSpace:
    """≡ arbiter-deeplearning4j :: ComputationGraphSpace — the graph
    twin: named layer/vertex spaces over the GraphBuilder DSL."""

    def __init__(self, global_spaces, inputs, nodes, outputs, input_types,
                 seed):
        self._globals = global_spaces
        self._inputs = inputs
        self._nodes = nodes          # [(name, LayerSpace|vertex, parents,
        #                               is_layer)]
        self._outputs = outputs
        self._input_types = input_types
        self._seed = seed

    class Builder:
        def __init__(self):
            self._globals = {}
            self._inputs = []
            self._nodes = []
            self._outputs = []
            self._input_types = None
            self._seed = 12345

        def seed(self, s):
            self._seed = int(s)
            return self

        def updater(self, u):
            self._globals["updater"] = u
            return self

        def weightInit(self, w):
            self._globals["weightInit"] = w
            return self

        def l2(self, v):
            self._globals["l2"] = v
            return self

        def addInputs(self, *names):
            self._inputs.extend(names)
            return self

        def addLayer(self, name, layer_space, *parents):
            self._nodes.append((name, layer_space, parents, True))
            return self

        def addVertex(self, name, vertex, *parents):
            self._nodes.append((name, vertex, parents, False))
            return self

        def setOutputs(self, *names):
            self._outputs.extend(names)
            return self

        def setInputTypes(self, *types):
            self._input_types = types
            return self

        def build(self):
            if not self._inputs or not self._outputs:
                raise ValueError("ComputationGraphSpace: addInputs() and "
                                 "setOutputs() are required")
            return ComputationGraphSpace(
                self._globals, list(self._inputs), list(self._nodes),
                list(self._outputs), self._input_types, self._seed)

    def collectLeaves(self):
        leaves = {}
        for k, v in self._globals.items():
            if isinstance(v, ParameterSpace):
                leaves[f"global.{k}"] = v
        for name, node, _, is_layer in self._nodes:
            if is_layer and isinstance(node, LayerSpace):
                leaves.update(node.leaves(f"node.{name}"))
        return leaves

    def getValue(self, cand):
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        b = NeuralNetConfiguration.Builder().seed(self._seed)
        for k, v in self._globals.items():
            val = _resolve(v, cand, f"global.{k}")
            if isinstance(v, UpdaterSpace):
                val = v.build(val)
            getattr(b, k)(val)
        g = b.graphBuilder()
        g.addInputs(*self._inputs)
        if self._input_types is not None:
            g.setInputTypes(*self._input_types)
        for name, node, parents, is_layer in self._nodes:
            if is_layer:
                # deep-copy raw confs — see MultiLayerSpace.getValue
                layer = (node.build(cand, f"node.{name}")
                         if isinstance(node, LayerSpace)
                         else copy.deepcopy(node))
                g.addLayer(name, layer, *parents)
            else:
                g.addVertex(name, copy.deepcopy(node), *parents)
        g.setOutputs(*self._outputs)
        return g.build()

    def model_builder(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        def build(cand):
            return ComputationGraph(self.getValue(cand)).init()

        return build
