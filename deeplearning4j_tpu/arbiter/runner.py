"""Hyperparameter optimization (≡ arbiter-core ::
optimize.generator.RandomSearchGenerator / GridSearchCandidateGenerator,
optimize.runner.LocalOptimizationRunner, scoring score functions) plus a
TPE-style Bayesian generator (the reference left Bayesian strategies to
plugins; here it's built in).

Candidates are plain dicts; the user supplies `model_builder(params) →
anything` and `scorer(model) → float`. The runner is sequential by
design — each candidate's training already saturates the chip; arbiter's
thread-pool parallelism maps to running candidates on separate hosts.
"""
from __future__ import annotations

import itertools
import time

import numpy as np

from deeplearning4j_tpu.arbiter.spaces import ParameterSpace


class CandidateGenerator:
    def __init__(self, search_space):
        self.space = dict(search_space)

    def has_more(self):
        return True

    def next_candidate(self):
        raise NotImplementedError

    def report(self, params, score):
        """Feedback hook for adaptive generators."""


class RandomSearchGenerator(CandidateGenerator):
    """≡ RandomSearchGenerator."""

    def __init__(self, search_space, seed=42):
        super().__init__(search_space)
        self.rng = np.random.default_rng(seed)

    def next_candidate(self):
        return {k: (v.sample(self.rng) if isinstance(v, ParameterSpace)
                    else v) for k, v in self.space.items()}


class GridSearchCandidateGenerator(CandidateGenerator):
    """≡ GridSearchCandidateGenerator — cartesian product, exhausted once."""

    def __init__(self, search_space, discretizationCount=5):
        super().__init__(search_space)
        axes = []
        for k, v in self.space.items():
            vals = v.grid(discretizationCount) if isinstance(
                v, ParameterSpace) else [v]
            axes.append([(k, val) for val in vals])
        self._product = list(itertools.product(*axes))
        self._idx = 0

    def has_more(self):
        return self._idx < len(self._product)

    def next_candidate(self):
        cand = dict(self._product[self._idx])
        self._idx += 1
        return cand


class TPEGenerator(CandidateGenerator):
    """Tree-structured Parzen Estimator: after `startupTrials` random
    candidates, split observed trials into good/bad by score quantile and
    sample candidates that maximize the good/bad density ratio (kernel
    density over each continuous/integer dim; categorical frequency for
    discrete)."""

    def __init__(self, search_space, seed=42, startupTrials=10, gamma=0.25,
                 nEI=24, minimize=True):
        super().__init__(search_space)
        self.rng = np.random.default_rng(seed)
        self.startup = int(startupTrials)
        self.gamma = float(gamma)
        self.nEI = int(nEI)
        self.minimize = minimize
        self.history = []  # (params, score)

    def report(self, params, score):
        self.history.append((params, float(score)))

    def _split(self):
        scores = np.asarray([s for _, s in self.history])
        order = np.argsort(scores if self.minimize else -scores)
        n_good = max(1, int(np.ceil(self.gamma * len(order))))
        good = [self.history[i][0] for i in order[:n_good]]
        bad = [self.history[i][0] for i in order[n_good:]] or good
        return good, bad

    @staticmethod
    def _kde_logpdf(x, samples, bw):
        d = (x - np.asarray(samples)[:, None]) / bw
        return np.log(np.maximum(
            np.exp(-0.5 * d * d).mean(0) / (bw * np.sqrt(2 * np.pi)),
            1e-300))

    def next_candidate(self):
        if len(self.history) < self.startup:
            return {k: (v.sample(self.rng) if isinstance(v, ParameterSpace)
                        else v) for k, v in self.space.items()}
        good, bad = self._split()
        out = {}
        for k, sp in self.space.items():
            if not isinstance(sp, ParameterSpace):
                out[k] = sp
                continue
            if hasattr(sp, "value"):  # FixedValue
                out[k] = sp.value
                continue
            g_vals = [p[k] for p in good]
            b_vals = [p[k] for p in bad]
            if hasattr(sp, "values"):  # discrete: sample by good-frequency
                vals, counts = np.unique(
                    [sp.values.index(v) for v in g_vals],
                    return_counts=True)
                probs = np.ones(len(sp.values))
                probs[vals] += counts * len(sp.values)
                probs /= probs.sum()
                out[k] = sp.values[int(self.rng.choice(len(sp.values),
                                                       p=probs))]
                continue
            # continuous/integer: draw nEI from the good KDE, keep best ratio
            lo, hi = float(sp.lo), float(sp.hi)
            log = getattr(sp, "log", False)
            tf = np.log if log else (lambda a: np.asarray(a, float))
            inv = np.exp if log else (lambda a: a)
            g = tf(g_vals)
            b = tf(b_vals)
            span = (tf([hi])[0] - tf([lo])[0]) or 1.0
            bw = max(span * 0.1, 1e-6)
            cand = g[self.rng.integers(len(g), size=self.nEI)] + \
                self.rng.normal(0, bw, self.nEI)
            cand = np.clip(cand, tf([lo])[0], tf([hi])[0])
            ratio = (self._kde_logpdf(cand, g, bw)
                     - self._kde_logpdf(cand, b, bw))
            best = inv(cand[int(np.argmax(ratio))])
            out[k] = int(round(best)) if isinstance(
                sp.lo, int) and not log else float(best)
        return out


class OptimizationResult:
    def __init__(self, params, score, model, index, duration_s):
        self.params = params
        self.score = score
        self.model = model
        self.index = index
        self.duration_s = duration_s


class LocalOptimizationRunner:
    """≡ optimize.runner.LocalOptimizationRunner."""

    def __init__(self, generator, model_builder, scorer, maxCandidates=10,
                 minimize=True, keep_models=False):
        self.generator = generator
        # a declarative network space (MultiLayerSpace /
        # ComputationGraphSpace) IS a model builder: no hand-written fn
        if hasattr(model_builder, "model_builder"):
            model_builder = model_builder.model_builder()
        self.model_builder = model_builder
        self.scorer = scorer
        self.maxCandidates = int(maxCandidates)
        self.minimize = minimize
        self.keep_models = keep_models
        self.results = []

    def execute(self):
        for i in range(self.maxCandidates):
            if not self.generator.has_more():
                break
            params = self.generator.next_candidate()
            t0 = time.perf_counter()
            model = self.model_builder(params)
            score = float(self.scorer(model))
            self.generator.report(params, score)
            self.results.append(OptimizationResult(
                params, score, model if self.keep_models else None, i,
                time.perf_counter() - t0))
        return self.bestResult()

    def bestResult(self):
        if not self.results:
            return None
        key = (lambda r: r.score) if self.minimize else (lambda r: -r.score)
        return min(self.results, key=key)

    def bestScore(self):
        r = self.bestResult()
        return None if r is None else r.score

    def numCandidatesCompleted(self):
        return len(self.results)
