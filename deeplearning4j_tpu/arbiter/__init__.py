"""Hyperparameter optimization (≡ arbiter)."""
from deeplearning4j_tpu.arbiter.spaces import (ContinuousParameterSpace,
                                               DiscreteParameterSpace,
                                               FixedValue,
                                               IntegerParameterSpace,
                                               ParameterSpace)
from deeplearning4j_tpu.arbiter.runner import (CandidateGenerator,
                                               GridSearchCandidateGenerator,
                                               LocalOptimizationRunner,
                                               OptimizationResult,
                                               RandomSearchGenerator,
                                               TPEGenerator)
from deeplearning4j_tpu.arbiter.network_spaces import (
    AdamSpace, ComputationGraphSpace, LayerSpace, MultiLayerSpace,
    NesterovsSpace, SgdSpace, UpdaterSpace)

__all__ = [
    "ContinuousParameterSpace", "DiscreteParameterSpace", "FixedValue",
    "IntegerParameterSpace", "ParameterSpace", "CandidateGenerator",
    "GridSearchCandidateGenerator", "LocalOptimizationRunner",
    "OptimizationResult", "RandomSearchGenerator", "TPEGenerator",
    "AdamSpace", "ComputationGraphSpace", "LayerSpace", "MultiLayerSpace",
    "NesterovsSpace", "SgdSpace", "UpdaterSpace",
]
