"""Hyperparameter optimization (≡ arbiter)."""
from deeplearning4j_tpu.arbiter.spaces import (ContinuousParameterSpace,
                                               DiscreteParameterSpace,
                                               FixedValue,
                                               IntegerParameterSpace,
                                               ParameterSpace)
from deeplearning4j_tpu.arbiter.runner import (CandidateGenerator,
                                               GridSearchCandidateGenerator,
                                               LocalOptimizationRunner,
                                               OptimizationResult,
                                               RandomSearchGenerator,
                                               TPEGenerator)

__all__ = [
    "ContinuousParameterSpace", "DiscreteParameterSpace", "FixedValue",
    "IntegerParameterSpace", "ParameterSpace", "CandidateGenerator",
    "GridSearchCandidateGenerator", "LocalOptimizationRunner",
    "OptimizationResult", "RandomSearchGenerator", "TPEGenerator",
]
