"""Hyperparameter spaces (≡ arbiter-core :: org.deeplearning4j.arbiter.
optimize.parameter.*: ContinuousParameterSpace, DiscreteParameterSpace,
IntegerParameterSpace, FixedValue).
"""
from __future__ import annotations

import numpy as np


class ParameterSpace:
    def sample(self, rng):
        raise NotImplementedError

    def grid(self, n):
        """Discretization for grid search."""
        raise NotImplementedError


class ContinuousParameterSpace(ParameterSpace):
    def __init__(self, minValue, maxValue, log=False):
        self.lo, self.hi = float(minValue), float(maxValue)
        self.log = log
        if log and self.lo <= 0:
            raise ValueError("log-scale space needs minValue > 0")

    def sample(self, rng):
        if self.log:
            return float(np.exp(rng.uniform(np.log(self.lo),
                                            np.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, n):
        if self.log:
            return list(np.exp(np.linspace(np.log(self.lo),
                                           np.log(self.hi), n)))
        return list(np.linspace(self.lo, self.hi, n))


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, minValue, maxValue):
        self.lo, self.hi = int(minValue), int(maxValue)

    def sample(self, rng):
        return int(rng.integers(self.lo, self.hi + 1))

    def grid(self, n):
        vals = np.unique(np.linspace(self.lo, self.hi, n).round().astype(int))
        return [int(v) for v in vals]


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, *values):
        if len(values) == 1 and isinstance(values[0], (list, tuple)):
            values = tuple(values[0])
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self, n):
        return list(self.values)


class FixedValue(ParameterSpace):
    def __init__(self, value):
        self.value = value

    def sample(self, rng):
        return self.value

    def grid(self, n):
        return [self.value]
