import os, threading, time
def bail():
    time.sleep(90); print("PROBE: init hang >90s (wedge signature)", flush=True); os._exit(3)
threading.Thread(target=bail, daemon=True).start()
t0 = time.time()
import jax
print("PROBE devices:", jax.devices(), f"{time.time()-t0:.1f}s", flush=True)
os._exit(0)
