"""Headline benchmark: ResNet-50 ImageNet-shape training throughput,
images/sec/chip (BASELINE.md: ≥ 360 img/s = nd4j-cuda V100-class fp32).

Runs on the real TPU (default JAX platform in this environment — axon).
Synthetic ImageNet-shaped data generated ON DEVICE (zero-egress env; the
host pipeline is benchmarked separately in tests) so the number measures
the training-step compute path: whole step = ONE jitted XLA executable
(fwd + bwd + SGD-momentum update, bf16 activations / fp32 masters).

Robustness (round 2): the axon PJRT plugin can hang *inside* device
initialization when the TPU tunnel is down — a hang no in-process timeout
can interrupt.  So this script self-forks: the parent re-runs itself as a
kill-able child subprocess (BENCH_CHILD=1) with a bounded per-attempt
timeout and retry/backoff, and ALWAYS prints exactly one JSON line on
stdout — with an "error" field when every attempt failed.  The child's
process group is killed on timeout so nothing is left holding the chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N, ...}
"""
from __future__ import annotations

import json
import os
import signal
import statistics
import subprocess
import sys
import time

def _want_tpu():
    """Declare this process a legitimate TPU consumer BEFORE the framework
    import, so the package-level attach guard
    (deeplearning4j_tpu.__init__._tpu_attach_guard) lets it through.
    Called from the RUN paths (main/child_main), not at module import:
    scripts that merely import bench helpers (exp_tpu_r4 imports
    bert_mfu_pct) must not inherit the opt-in as a side effect."""
    os.environ.setdefault("DL4J_TPU_WANT_TPU", "1")


BASELINE_IMG_S = 360.0
METRIC = "resnet50_imagenet_images_per_sec_per_chip"


def _median_of_windows(run_window, k=5, max_k=9, spread_limit=0.20):
    """Median over k independent timed windows.

    VERDICT r4 #2: the sub-20 ms-step rows (LeNet, char-LSTM) swing ~2x
    between back-to-back single-window runs — a point sample of that
    distribution is not a measurement. Runs k windows, keeps adding
    windows while the spread ((max-min)/median) exceeds spread_limit (up
    to max_k), and returns (median, all_window_values, spread)."""
    vals = [run_window(i) for i in range(k)]
    while True:
        med = statistics.median(vals)
        spread = (max(vals) - min(vals)) / med
        if spread <= spread_limit or len(vals) >= max_k:
            return med, vals, spread
        vals.append(run_window(len(vals)))


def _windowed_rate(step, carry0, step_args, rng_key, steps, units,
                   start_index, k_windows, windows_out):
    """The timed-window protocol shared by every bench row.

    Threads (params, opt_state, net_state) through `steps` enqueued train
    steps per window with ONE device->host sync (float(loss)) closing each
    window; with k_windows>1, takes the median over independent windows
    (_median_of_windows) and records the window values + spread into
    windows_out. `units` = work items per step (images, chars). Returns
    (units_per_sec, final_loss, final_carry)."""
    import jax

    carry = {"t": carry0, "loss": None, "i": start_index}

    def timed_window(_w):
        p, o, s = carry["t"]
        i0 = carry["i"]
        t0 = time.perf_counter()
        for i in range(steps):
            p, o, s, loss = step(p, o, s, *step_args, None, None,
                                 jax.random.fold_in(rng_key, i0 + i))
        lv = float(loss)   # ONE device->host sync closes the window
        dtw = (time.perf_counter() - t0) / steps
        carry.update(t=(p, o, s), loss=lv, i=i0 + steps)
        return units / dtw

    if k_windows > 1:
        rate, vals, spread = _median_of_windows(timed_window, k=k_windows)
        if windows_out is not None:
            windows_out["windows"] = [round(v, 1) for v in vals]
            windows_out["spread_pct"] = round(spread * 100, 1)
    else:
        rate = timed_window(0)
    return rate, carry["loss"], carry["t"]


def _bench_zoo_model(model_cls, batch, steps, warmup, input_hw=224,
                     classes=1000, lr=0.1, roofline_out=None,
                     k_windows=1, windows_out=None):
    """img/s for one zoo CNN: whole step = ONE jitted XLA executable.

    roofline_out: optional dict filled with XLA cost-analysis roofline
    fields (step bytes-accessed, HBM-bound step time) so the artifact can
    state how close the measured step is to the memory bound — the r3/r4
    profiles show ResNet-50 at batch 256 is HBM-bandwidth dominated.

    k_windows>1: report the MEDIAN img/s over k independent timed windows
    of `steps` steps each (one device sync per window), recording the
    window values + spread into windows_out — the statistically
    defensible form for sub-20 ms steps whose single-window numbers swing
    with tunnel dispatch jitter."""
    warmup = max(1, warmup)   # compile must finish before the timed window
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.updaters import Nesterovs

    # BENCH_MOMENTUM_DTYPE=bfloat16 halves optimizer-state HBM traffic
    # (fp32 masters kept; loss parity tested in test_multilayer)
    mdt = os.environ.get("BENCH_MOMENTUM_DTYPE") or None
    model = model_cls(numClasses=classes, dataType="bfloat16",
                      inputShape=(input_hw, input_hw, 3),
                      updater=Nesterovs(lr, 0.9, momentumDtype=mdt))
    net = model.init()
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    x = jax.random.uniform(kx, (batch, input_hw, input_hw, 3), jnp.float32)
    y = jax.nn.one_hot(jax.random.randint(ky, (batch,), 0, classes), classes,
                       dtype=jnp.float32)
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    is_graph = isinstance(net, ComputationGraph)
    ins = {"input": x} if is_graph else x
    labs = [y] if is_graph else y
    step = net._train_step
    params, opt, state = net._params, net._opt_state, net._state
    rng = jax.random.PRNGKey(1)

    # Sync via float(loss): a device->host transfer cannot complete before
    # the step chain finishes. (block_until_ready on this experimental PJRT
    # plugin returns early; the transfer-based sync measures true step time.)
    t_compile = time.perf_counter()
    for i in range(warmup):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, i))
    float(loss)
    compile_s = time.perf_counter() - t_compile

    rate, final_loss, (params, opt, state) = _windowed_rate(
        step, (params, opt, state), (ins, labs), rng, steps, batch,
        100, k_windows, windows_out)
    dt = batch / rate
    if roofline_out is not None:
        try:
            # bytes-accessed from the compiled executable's cost analysis
            # (no profiling pass needed); the lower().compile() here hits
            # the persistent compile cache, so it costs seconds, not a
            # fresh compile. 819 GB/s = v5e nominal HBM bandwidth; the
            # round-4 XStat profile measured individual step fusions
            # sustaining 680-840 GB/s, corroborating that denominator.
            ca = step.lower(params, opt, state, ins, labs, None, None,
                            rng).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):   # older per-device form
                ca = ca[0]
            step_bytes = float(ca.get("bytes accessed", 0.0))
            if step_bytes > 0:
                bound_ms = step_bytes / 819e9 * 1e3
                roofline_out.update({
                    "step_bytes": int(step_bytes),
                    "hbm_bound_ms": round(bound_ms, 1),
                    "step_ms": round(dt * 1e3, 1),
                    "pct_of_hbm_bound": round(bound_ms / (dt * 1e3) * 100,
                                              1),
                })
            else:
                # keep the artifact self-describing: absent fields must
                # be distinguishable from a never-attempted roofline
                roofline_out["roofline_error"] = \
                    "cost_analysis had no 'bytes accessed'"
        except Exception as e:  # noqa: BLE001 — cost analysis is
            # best-effort; never let it take down the measurement
            roofline_out["roofline_error"] = str(e)[:160]
    return batch / dt, dt, compile_s, final_loss


def bert_mfu_pct(steps_s, tokens_per_step):
    """~6 FLOP/param/token fwd+bwd (3x2), 110M params, 197 TFLOP/s v5e
    bf16 peak — the ONE place this formula lives (exp_tpu_r4 imports it)."""
    return steps_s * 6 * 110e6 * tokens_per_step / 197e12 * 100


def _bench_bert_finetune(batch=None, seq=None, steps=10, warmup=2):
    """BERT-base classification fine-tune steps/s (flash attention on TPU):
    fwd + bwd + Adam in one jitted executable."""
    batch = batch or int(os.environ.get("BENCH_BERT_BATCH", "32"))
    seq = seq or int(os.environ.get("BENCH_BERT_SEQ", "128"))
    warmup = max(1, warmup)   # compile must finish before the timed window
    import jax
    import jax.numpy as jnp
    import optax

    from deeplearning4j_tpu.models.bert import (bert_base,
                                                classification_loss,
                                                init_bert_params)

    cfg = bert_base()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adam(2e-5)
    opt = tx.init(params)
    k_ids, k_lab, k_len = jax.random.split(jax.random.PRNGKey(1), 3)
    ids = jax.random.randint(k_ids, (batch, seq), 0, cfg.vocab_size)
    labels = jax.random.randint(k_lab, (batch,), 0, cfg.num_labels)
    # realistic fine-tune: ragged padding masks (flash kernels' masked path)
    lengths = jax.random.randint(k_len, (batch,), seq // 2, seq + 1)
    mask = (jnp.arange(seq)[None, :] < lengths[:, None]).astype(jnp.float32)
    batch_d = {"input_ids": ids, "labels": labels, "attention_mask": mask}

    @jax.jit
    def step(p, o, rng):
        loss, g = jax.value_and_grad(
            lambda pp: classification_loss(cfg, pp, batch_d, train=True,
                                           rng=rng))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    rng = jax.random.PRNGKey(2)
    t_compile = time.perf_counter()
    for i in range(warmup):
        params, opt, loss = step(params, opt, jax.random.fold_in(rng, i))
    float(loss)
    compile_s = time.perf_counter() - t_compile
    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, loss = step(params, opt, jax.random.fold_in(rng, 9 + i))
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    return 1.0 / dt, dt, compile_s, batch * seq


def _bench_lenet(batch=256, steps=60, warmup=3, windows_out=None):
    """LeNet-5 MNIST-shape img/s (BASELINE.md: sub-second synthetic epoch).
    60 steps per window (sub-10ms steps need the one end-of-window sync
    round-trip amortized over many steps), median of >=5 windows with the
    spread recorded in the artifact (VERDICT r4 #2)."""
    from deeplearning4j_tpu.models.zoo import LeNet
    return _bench_zoo_model(LeNet, batch, steps, warmup, input_hw=28,
                            classes=10, lr=0.01, k_windows=5,
                            windows_out=windows_out)


def _bench_char_lstm(batch=256, seq=128, hidden=512, steps=None, warmup=2,
                     windows_out=None, k_windows=5):
    """GravesLSTM char-RNN training: chars/s through a 2-layer LSTM built
    on the builder DSL (BASELINE.md row: jitted lax.scan ≥ parity).

    Defaults are the round-4 on-chip sweep winner (exp_tpu_r4 lstm,
    2026-07-31: batch 256 x unroll 8 x bf16 = 1.75M chars/s; see
    BENCH.md) — override with BENCH_LSTM_{BATCH,UNROLL,DTYPE}.

    steps defaults high (50): with fast steps the ONE end-of-window sync
    round-trip must be amortized over many steps or it dominates dt."""
    if steps is None:
        steps = int(os.environ.get("BENCH_LSTM_STEPS", "50"))
    batch = int(os.environ.get("BENCH_LSTM_BATCH", batch))
    import jax
    import numpy as np

    from deeplearning4j_tpu.nn import (InputType, NeuralNetConfiguration,
                                       RmsProp)
    from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    warmup = max(1, warmup)   # compile must finish before the timed window
    vocab = 80
    unroll = int(os.environ.get("BENCH_LSTM_UNROLL", "8"))
    dtype = os.environ.get("BENCH_LSTM_DTYPE", "bfloat16")
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(RmsProp(1e-3)).weightInit("xavier")
            .dataType(dtype)
            .list()
            .layer(LSTM(nOut=hidden, activation="tanh", scanUnroll=unroll))
            .layer(LSTM(nOut=hidden, activation="tanh", scanUnroll=unroll))
            .layer(RnnOutputLayer(nOut=vocab, lossFunction="mcxent",
                                  activation="softmax"))
            .setInputType(InputType.recurrent(vocab, seq))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq + 1))
    x = np.eye(vocab, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]
    # Same methodology as every other row: data device-resident, the step
    # loop enqueues the ONE jitted executable, a single float(loss) sync
    # closes the timed window. The previous net.fit(ds)-per-step loop paid
    # a ~5 MB host->device upload AND a full tunnel round-trip per step —
    # host/tunnel overhead, not device time, dominated the round-3 number
    # (4799 chars/s looked like 13 ms/scan-iter; the device was idle).
    xd, yd = jax.device_put(x), jax.device_put(y)
    step = net._train_step
    params, opt, state = net._params, net._opt_state, net._state
    key = jax.random.PRNGKey(7)
    t0 = time.perf_counter()
    for i in range(warmup):
        params, opt, state, loss = step(params, opt, state, xd, yd, None,
                                        None, jax.random.fold_in(key, i))
    float(loss)
    compile_s = time.perf_counter() - t0

    # median of >=5 independent windows + recorded spread (VERDICT r4 #2);
    # sweep/trace callers (exp_tpu_r4) pass k_windows=1 for single-window
    rate, _, _ = _windowed_rate(step, (params, opt, state), (xd, yd), key,
                                steps, batch * seq, 99, k_windows,
                                windows_out)
    return rate, batch * seq / rate, compile_s


def child_main():
    """The actual measurement (runs in a kill-able subprocess)."""
    _want_tpu()
    t_start = time.perf_counter()
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    extras = os.environ.get("BENCH_EXTRA", "vgg16,bert,lenet,lstm")

    import jax

    from deeplearning4j_tpu.util.hostkey import enable_compile_cache
    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))

    dev = jax.devices()[0]
    print(f"# device: {dev} platform={dev.platform}", file=sys.stderr, flush=True)

    from deeplearning4j_tpu.models.zoo import ResNet50, VGG16

    fused = os.environ.get("DL4J_TPU_FUSE_CONV_BN", "off")
    roofline = {}
    try:
        img_s, dt, compile_s, final_loss = _bench_zoo_model(
            ResNet50, batch, steps, warmup, roofline_out=roofline)
    except Exception as e:  # noqa: BLE001
        # the conv1x1+BN Pallas fusion is the newest moving part — if it
        # fails on this chip/toolchain, record why and fall back to the
        # pure-XLA path rather than zeroing the headline. Only applies
        # when fusion was actually on; otherwise the failure is real.
        from deeplearning4j_tpu.nn.fused import fusion_enabled
        if not fusion_enabled():
            raise
        print(f"# fused path failed ({e}); retrying unfused",
              file=sys.stderr, flush=True)
        os.environ["DL4J_TPU_FUSE_CONV_BN"] = "0"
        fused = f"fallback-unfused: {str(e)[:120]}"
        img_s, dt, compile_s, final_loss = _bench_zoo_model(
            ResNet50, batch, steps, warmup, roofline_out=roofline)
    # MFU accounting: ResNet-50 fwd+bwd ≈ 3 × 4.1 GFLOP/img = 12.3 GFLOP/img;
    # v5e peak 197 TFLOP/s bf16
    mfu = img_s * 12.3e9 / 197e12 * 100
    result = {
        "metric": METRIC,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu_pct": round(mfu, 1),
        "mfu_note": "img_s*12.3GFLOP/img / 197 TFLOP/s v5e bf16 peak",
        "conv1x1_bn_fusion": fused,
    }
    result.update(roofline)
    print(f"# resnet50: batch={batch} steps={steps} "
          f"step_time={dt*1000:.1f}ms loss={final_loss:.3f} "
          f"warmup+compile={compile_s:.1f}s mfu={mfu:.1f}%",
          file=sys.stderr, flush=True)

    # secondary BASELINE.md configs — extra JSON fields, headline unchanged;
    # a failing extra never takes down the headline number, and extras are
    # skipped when cold compiles already ate the attempt window
    extra_deadline = float(os.environ.get("BENCH_EXTRA_DEADLINE", "300"))

    def _over_budget():
        return time.perf_counter() - t_start > extra_deadline

    def _emit_partial():
        # Incremental checkpoint: if the parent (or the driver above it)
        # kills this child mid-extras, the parent recovers the LAST of
        # these lines instead of zeroing the whole artifact. Never starts
        # with "{" so the success path (first "{" line) ignores it.
        print(f"#partial# {json.dumps(result)}", flush=True)

    _emit_partial()

    if "vgg16" in extras:
        if _over_budget():
            result["vgg16_error"] = "skipped: attempt time budget exhausted"
        else:
            try:
                vbatch = int(os.environ.get("BENCH_VGG_BATCH", "128"))
                v_img_s, v_dt, v_c, _ = _bench_zoo_model(
                    VGG16, vbatch, max(steps // 2, 5), warmup, lr=0.01)
                result["vgg16_img_s"] = round(v_img_s, 2)
                result["vgg16_vs_baseline"] = round(v_img_s / 190.0, 3)
                # VGG16 fwd ~15.5 GFLOP/img, fwd+bwd ~3x
                result["vgg16_mfu_pct"] = round(
                    v_img_s * 3 * 15.5e9 / 197e12 * 100, 1)
                print(f"# vgg16: batch={vbatch} step={v_dt*1000:.1f}ms "
                      f"compile={v_c:.1f}s", file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001 — diagnostic field
                result["vgg16_error"] = str(e)[:200]
    _emit_partial()
    # bert runs before the lower-value lenet/lstm rows so the time budget
    # never skips the flagship fine-tune number in their favour
    if "bert" in extras:
        if _over_budget():
            result["bert_error"] = "skipped: attempt time budget exhausted"
        else:
            try:
                b_steps_s, b_dt, b_c, b_tokens = _bench_bert_finetune()
                result["bert_ft_steps_s"] = round(b_steps_s, 2)
                result["bert_ft_note"] = (
                    f"BERT-base tokens/step={b_tokens} masked flash attn")
                result["bert_ft_mfu_pct"] = round(
                    bert_mfu_pct(b_steps_s, b_tokens), 1)
                print(f"# bert: step={b_dt*1000:.1f}ms compile={b_c:.1f}s",
                      file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001
                result["bert_error"] = str(e)[:200]
    _emit_partial()
    if "lenet" in extras:
        if _over_budget():
            result["lenet_error"] = "skipped: attempt time budget exhausted"
        else:
            try:
                lw = {}
                l_img_s, l_dt, l_c, _ = _bench_lenet(windows_out=lw)
                result["lenet_img_s"] = round(l_img_s, 2)
                result["lenet_windows"] = lw.get("windows")
                result["lenet_spread_pct"] = lw.get("spread_pct")
                print(f"# lenet: step={l_dt*1000:.2f}ms compile={l_c:.1f}s "
                      f"windows={lw}", file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001
                result["lenet_error"] = str(e)[:200]
    _emit_partial()
    if "lstm" in extras:
        if _over_budget():
            result["lstm_error"] = "skipped: attempt time budget exhausted"
        else:
            try:
                cw = {}
                c_s, c_dt, c_c = _bench_char_lstm(windows_out=cw)
                result["char_lstm_chars_s"] = round(c_s, 2)
                result["char_lstm_windows"] = cw.get("windows")
                result["char_lstm_spread_pct"] = cw.get("spread_pct")
                print(f"# char-lstm: step={c_dt*1000:.1f}ms "
                      f"compile={c_c:.1f}s windows={cw}",
                      file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001
                result["lstm_error"] = str(e)[:200]

    print(json.dumps(result))


def _preflight_child():
    """BENCH_PREFLIGHT=1 child body: initialize the device and print
    the '# device:' marker — nothing else. A tunnel-wedge hang dies
    here in seconds of timeout instead of a full 560 s attempt."""
    _want_tpu()
    import jax

    dev = jax.devices()[0]
    print(f"# device: {dev} platform={dev.platform}", flush=True)


def _preflight(timeout_s: float):
    """Probe device init in a kill-able child BEFORE burning full
    measurement attempts. Returns (ok, diagnostic, is_outage):
    is_outage is True ONLY for the known axon-tunnel signature (init
    HANG with no device line — what BENCH_r05 spent 2×560 s timing out
    on); a child that CRASHES is a code problem and must not be
    reported as infrastructure."""
    env = dict(os.environ)
    env["BENCH_PREFLIGHT"] = "1"
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, start_new_session=True, env=env)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, _ = proc.communicate()
        if "# device:" in (out or ""):
            # device came up but the child was slow to exit — not the
            # outage signature; let the real attempts proceed
            return True, "preflight slow but device initialized", False
        return (False, f"device init hung for {timeout_s:.0f}s with no "
                       f"device line (tunnel outage signature)", True)
    if proc.returncode == 0 and "# device:" in (out or ""):
        return True, "", False
    return (False, f"preflight rc={proc.returncode}; "
                   f"output tail: {(out or '')[-300:]}", False)


def _outage_artifact(errors):
    """The zero-value artifact with the outage note pointing at the
    freshest code-side local measurement."""
    out = {
        "metric": METRIC,
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": " | ".join(errors)[-900:],
    }
    import glob
    locals_ = glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_r*_local.json"))
    note = ("axon TPU tunnel outage signature (init hang, no device "
            "line) — see BENCH.md outage log")
    if locals_:
        newest = os.path.basename(max(locals_, key=os.path.getmtime))
        note += (f"; freshest code-side measurements: {newest} "
                 "(green full-extras run on a healthy tunnel)")
    out["note"] = note
    return out


def _run_attempt(timeout_s: float):
    """Run one child attempt.

    Returns (json_dict | None, diagnostic_str, partial_dict | None); the
    diagnostic contains the literal "# device:" marker iff the child got
    far enough to initialize the chip (distinguishes a slow measurement
    from the tunnel-wedge init hang)."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"

    # If THIS parent is killed (SIGTERM/SIGINT — e.g. an outer `timeout`),
    # take the child's whole process group down too: an orphaned child in
    # its own session keeps the TPU tunnel's grant claimed and wedges the
    # chip for every later process (observed: hours-long outage). Handlers
    # go in BEFORE Popen so there is no orphanable window.
    proc_holder = []

    def _reap(signum, frame):
        for p in proc_holder:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        raise SystemExit(128 + signum)

    old_term = signal.signal(signal.SIGTERM, _reap)
    old_int = signal.signal(signal.SIGINT, _reap)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True, env=env)
    proc_holder.append(proc)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # Kill the whole process group so nothing is left holding the chip.
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        dev = "yes" if "# device:" in err else "no"
        return (None, f"timeout after {timeout_s:.0f}s; device_line={dev}; "
                f"stderr tail: {err[-500:]}", _last_partial(out))
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
    if proc.returncode != 0:
        return (None, f"rc={proc.returncode}; stderr tail: {err[-500:]}",
                _last_partial(out))
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                sys.stderr.write(err)
                return json.loads(line), "", None
            except json.JSONDecodeError:
                continue
    return (None, f"no JSON line in child stdout; stdout: {out[-300:]!r}",
            _last_partial(out))


def _last_partial(out: str):
    """Most complete measurement checkpoint a killed/failed child printed
    (see child_main's _emit_partial) — salvages the headline when the
    attempt died mid-extras instead of zeroing the artifact."""
    best = None
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("#partial# "):
            try:
                best = json.loads(line[len("#partial# "):])
            except json.JSONDecodeError:
                continue
    return best


def main():
    _want_tpu()
    if os.environ.get("BENCH_PREFLIGHT") == "1":
        _preflight_child()
        return
    if os.environ.get("BENCH_CHILD") == "1":
        child_main()
        return

    # fast-fail device preflight: a ~90 s kill-able init probe before
    # any full attempt — the known tunnel-outage signature (init hang,
    # no device line) records its verdict immediately instead of
    # burning 2×560 s timing out (BENCH_PREFLIGHT_TIMEOUT=0 disables)
    pf_timeout = float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", "90"))
    if pf_timeout > 0:
        ok, diag, is_outage = _preflight(pf_timeout)
        if not ok:
            print(f"# preflight failed: {diag}", file=sys.stderr,
                  flush=True)
            if is_outage:
                out = _outage_artifact([f"preflight: {diag}"])
            else:
                # child CRASHED (code problem, not infrastructure):
                # plain error artifact, no outage note
                out = {"metric": METRIC, "value": 0.0, "unit": "img/s",
                       "vs_baseline": 0.0,
                       "error": f"preflight: {diag}"[-900:]}
            print(json.dumps(out))
            return
        print("# preflight: device ok", file=sys.stderr, flush=True)

    attempts = int(os.environ.get("BENCH_ATTEMPTS", "3"))
    # must exceed the remote compile service's own ~500 s timeout: a
    # SIGKILL while a compile RPC is in flight wedges the tunnel for hours
    # (BENCH.md outage log), so let a slow compile fail on its own first
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "560"))
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE", "1500"))
    backoff = 15.0

    errors = []
    partial = None
    for i in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining <= 5:
            errors.append("wall-clock deadline reached")
            break
        if i > 0 and remaining < attempt_timeout:
            # never truncate a RETRY below a full attempt window: killing
            # the child under the ~500 s compile-RPC timeout risks the
            # mid-compile SIGKILL wedge this harness exists to avoid
            errors.append("remaining window shorter than a full attempt")
            break
        t = min(attempt_timeout, remaining)
        print(f"# attempt {i + 1}/{attempts} (timeout {t:.0f}s)",
              file=sys.stderr, flush=True)
        result, diag, att_partial = _run_attempt(t)
        if result is not None:
            print(json.dumps(result))
            return
        if att_partial is not None and (
                partial is None or len(att_partial) >= len(partial)):
            partial = att_partial
        errors.append(f"attempt {i + 1}: {diag}")
        print(f"# {errors[-1]}", file=sys.stderr, flush=True)
        # only back off when a FULL next attempt still fits afterwards —
        # the retry loop above refuses truncated windows anyway
        if (i + 1 < attempts
                and deadline - time.monotonic() - backoff >= attempt_timeout):
            time.sleep(backoff)
            backoff *= 2

    if partial is not None and partial.get("value"):
        # a measured headline beats a zeroed artifact: report the last
        # checkpoint of the furthest-along attempt, flagged as truncated
        diag = " | ".join(e.split(";", 1)[0] for e in errors)
        partial["note"] = ("attempt killed mid-extras; fields present were "
                           "measured, missing extras were not reached — "
                           + diag[-300:])
        print(json.dumps(partial))
        return

    ran = [e for e in errors if e.startswith("attempt")]
    if ran and all("timeout" in e and "device_line=yes" not in e
                   for e in ran):
        # every attempt hung with no "# device:" line — the known axon
        # tunnel-wedge signature, not a framework failure (BENCH.md
        # outage log; last driver-verified run BENCH_r02.json)
        out = _outage_artifact(errors)
    else:
        out = {
            "metric": METRIC,
            "value": 0.0,
            "unit": "img/s",
            "vs_baseline": 0.0,
            "error": " | ".join(errors)[-900:],
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
