"""Headline benchmark: ResNet-50 ImageNet-shape training throughput,
images/sec/chip (BASELINE.md: ≥ 360 img/s = nd4j-cuda V100-class fp32).

Runs on the real TPU (default JAX platform in this environment — axon).
Synthetic ImageNet-shaped data generated ON DEVICE (zero-egress env; the
host pipeline is benchmarked separately in tests) so the number measures
the training-step compute path: whole step = ONE jitted XLA executable
(fwd + bwd + SGD-momentum update, bf16 activations / fp32 masters).

Robustness (round 2): the axon PJRT plugin can hang *inside* device
initialization when the TPU tunnel is down — a hang no in-process timeout
can interrupt.  So this script self-forks: the parent re-runs itself as a
kill-able child subprocess (BENCH_CHILD=1) with a bounded per-attempt
timeout and retry/backoff, and ALWAYS prints exactly one JSON line on
stdout — with an "error" field when every attempt failed.  The child's
process group is killed on timeout so nothing is left holding the chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N, ...}
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

BASELINE_IMG_S = 360.0
METRIC = "resnet50_imagenet_images_per_sec_per_chip"


def child_main():
    """The actual measurement (runs in a kill-able subprocess)."""
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    dev = jax.devices()[0]
    print(f"# device: {dev} platform={dev.platform}", file=sys.stderr, flush=True)

    from deeplearning4j_tpu.models.zoo import ResNet50
    from deeplearning4j_tpu.nn.updaters import Nesterovs

    model = ResNet50(numClasses=1000, dataType="bfloat16",
                     inputShape=(224, 224, 3),
                     updater=Nesterovs(0.1, 0.9))
    net = model.init()

    # on-device synthetic batch (static): uniform images + random one-hots
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    x = jax.random.uniform(kx, (batch, 224, 224, 3), jnp.float32)
    labels = jax.random.randint(ky, (batch,), 0, 1000)
    y = jax.nn.one_hot(labels, 1000, dtype=jnp.float32)

    ins = {"input": x}
    labs = [y]

    step = net._train_step
    params, opt, state = net._params, net._opt_state, net._state
    rng = jax.random.PRNGKey(1)

    # Sync via float(loss): a device->host transfer cannot complete before
    # the step chain finishes. (Empirically, block_until_ready returned in
    # ~1.6ms/step here — ~18x over v5e peak FLOPs, i.e. it did not wait on
    # this experimental PJRT plugin; the transfer-based sync measures the
    # true step time.)
    t_compile = time.perf_counter()
    for i in range(warmup):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, i))
    float(loss)
    compile_s = time.perf_counter() - t_compile
    print(f"# warmup+compile={compile_s:.1f}s", file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, 100 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    result = {
        "metric": METRIC,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }
    print(json.dumps(result))
    print(f"# batch={batch} steps={steps} step_time={dt/steps*1000:.1f}ms "
          f"loss={final_loss:.3f} warmup+compile={compile_s:.1f}s "
          f"platform={dev.platform}", file=sys.stderr, flush=True)


def _run_attempt(timeout_s: float):
    """Run one child attempt; return (json_dict | None, diagnostic_str)."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True, env=env)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # Kill the whole process group so nothing is left holding the chip.
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        return None, f"timeout after {timeout_s:.0f}s; stderr tail: {err[-500:]}"
    if proc.returncode != 0:
        return None, f"rc={proc.returncode}; stderr tail: {err[-500:]}"
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                sys.stderr.write(err)
                return json.loads(line), ""
            except json.JSONDecodeError:
                continue
    return None, f"no JSON line in child stdout; stdout: {out[-300:]!r}"


def main():
    if os.environ.get("BENCH_CHILD") == "1":
        child_main()
        return

    attempts = int(os.environ.get("BENCH_ATTEMPTS", "3"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "420"))
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE", "1500"))
    backoff = 15.0

    errors = []
    for i in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining <= 5:
            errors.append("wall-clock deadline reached")
            break
        t = min(attempt_timeout, remaining)
        print(f"# attempt {i + 1}/{attempts} (timeout {t:.0f}s)",
              file=sys.stderr, flush=True)
        result, diag = _run_attempt(t)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"attempt {i + 1}: {diag}")
        print(f"# {errors[-1]}", file=sys.stderr, flush=True)
        if i + 1 < attempts and deadline - time.monotonic() > backoff:
            time.sleep(backoff)
            backoff *= 2

    print(json.dumps({
        "metric": METRIC,
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": " | ".join(errors)[-900:],
    }))


if __name__ == "__main__":
    main()
