"""Headline benchmark: ResNet-50 ImageNet-shape training throughput,
images/sec/chip (BASELINE.md: ≥ 360 img/s = nd4j-cuda V100-class fp32).

Runs on the real TPU (default JAX platform in this environment — axon).
Synthetic ImageNet-shaped data generated ON DEVICE (zero-egress env; the
host pipeline is benchmarked separately in tests) so the number measures
the training-step compute path: whole step = ONE jitted XLA executable
(fwd + bwd + SGD-momentum update, bf16 activations / fp32 masters).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N/360}
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 360.0


def main():
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from deeplearning4j_tpu.models.zoo import ResNet50
    from deeplearning4j_tpu.nn.updaters import Nesterovs

    model = ResNet50(numClasses=1000, dataType="bfloat16",
                     inputShape=(224, 224, 3),
                     updater=Nesterovs(0.1, 0.9))
    net = model.init()

    # on-device synthetic batch (static): uniform images + random one-hots
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    x = jax.random.uniform(kx, (batch, 224, 224, 3), jnp.float32)
    labels = jax.random.randint(ky, (batch,), 0, 1000)
    y = jax.nn.one_hot(labels, 1000, dtype=jnp.float32)

    ins = {"input": x}
    labs = [y]

    step = net._train_step
    params, opt, state = net._params, net._opt_state, net._state
    rng = jax.random.PRNGKey(1)

    # Sync via float(loss): a device->host transfer cannot complete before
    # the step chain finishes. (Empirically, block_until_ready returned in
    # ~1.6ms/step here — ~18x over v5e peak FLOPs, i.e. it did not wait on
    # this experimental PJRT plugin; the transfer-based sync measures 108ms/
    # step, consistent with ~27% MXU utilization.)
    t_compile = time.perf_counter()
    for i in range(warmup):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, i))
    float(loss)
    compile_s = time.perf_counter() - t_compile

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, 100 + i))
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    result = {
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }
    print(json.dumps(result))
    print(f"# batch={batch} steps={steps} step_time={dt/steps*1000:.1f}ms "
          f"loss={final_loss:.3f} warmup+compile={compile_s:.1f}s "
          f"device={jax.devices()[0]}", file=sys.stderr)


if __name__ == "__main__":
    main()
