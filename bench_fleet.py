#!/usr/bin/env python
"""CPU microbench: fleet routing overhead at equal total slots, plus
time-to-healthy after a replica kill (generation/fleet.py — ISSUE 20),
one JSON artifact.

Two claims under measurement:

1. **Routing is (nearly) free.** A 3-replica fleet of 2-slot servers
   versus ONE bare 2-slot replica over the same 24-request mixed
   workload (greedy + sampled + top-k). The headline `value` is the
   aggregate tok/s RATIO (fleet / single replica). On this single-core
   CPU host the three replicas time-share one core, so the ratio sits
   near 1.0 — what the number guards is ROUTER OVERHEAD (relay
   threads, health scans, the dispatch hook): a collapse means the
   routing hot path regressed. On an N-core (or N-device) host the
   same ratio approaches N — the artifact records the single-core
   floor, not the parallel ceiling. Streams must also be
   BIT-IDENTICAL across the arms: fleet-wide admission ids over
   seed-aligned replicas make a stream a pure function of (seed,
   admit id, prompt, sampling config), so window 0's fleet streams
   must equal the bare replica's token for token — routing must never
   perturb sampling.

2. **Replica loss is repaired in warm-spin-up time.** After the timed
   windows, each measurement kills one idle replica (`_die`), submits
   a probe request (served by a survivor; the router's background
   reviver kicks on the same dispatch), and clocks until the roster is
   back to full healthy strength. Every replacement must report ZERO
   live compiles — spin-up is a disk read from the shared
   FunctionStore, not a compile storm.

Methodology is bench.py's median-of->=5-windows + recorded-spread
(VERDICT r4: a point sample of a +-20%-noise distribution is not a
measurement) for BOTH metrics. `scripts/check_bench_regression.py`
gates successive BENCH_FLEET_* artifacts on the headline via its
`paths` knob (MULTIHOST/PAGED precedent — a ~1.0x overhead ratio must
never compete with img/s headlines in the default BENCH_* trajectory).

Run:  JAX_PLATFORMS=cpu python bench_fleet.py
"""
import argparse
import json
import os
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

# bench.py is import-safe (no device init at module scope) — share THE
# windowing helper instead of copying it, so the methodology cannot
# drift between benches
from bench import _median_of_windows

from deeplearning4j_tpu.generation import FleetRouter, GenerationServer
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam

VOCAB = 16
HIDDEN = 128
REPLICAS = 3
REPLICA_SLOTS = 2
SINGLE_SLOTS = REPLICA_SLOTS     # the single arm IS one bare replica
N_REQUESTS = 24
SEED = 11

# mixed sampling methods: the cross-arm identity assertion must cover
# the admission-id-dependent paths (sampled rngs), not just greedy.
# Budgets are sized so one window decodes ~500 tokens — long enough
# that the per-window rate is not a point sample of dispatch jitter
_MIX = [
    dict(prompt=[1, 2, 3], max_new_tokens=24),
    dict(prompt=[5, 4], max_new_tokens=20, method="sample",
         temperature=0.8),
    dict(prompt=[7, 3, 2, 1], max_new_tokens=24, method="top_k",
         temperature=0.9, top_k=3),
    dict(prompt=[2, 2, 5], max_new_tokens=16),
]
WORKLOAD = [dict(_MIX[i % len(_MIX)]) for i in range(N_REQUESTS)]


def _build_net():
    return MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
         .weightInit("xavier").list()
         .layer(LSTM(nOut=HIDDEN, activation="tanh"))
         .layer(RnnOutputLayer(lossFunction="mcxent", nOut=VOCAB,
                               activation="softmax"))
         .setInputType(InputType.recurrent(VOCAB)).build())).init()


def _server(net, cache_dir, slots):
    return GenerationServer(
        net, slots=slots, cache_lengths=[48], prompt_buckets=[8],
        method="greedy", seed=SEED, exec_cache_dir=cache_dir)


def _serve_mix(submit):
    """One timed window: submit the whole 24-request mix through
    `submit`, consume every stream. Returns (streams, tok/s)."""
    t0 = time.perf_counter()
    reqs = [submit(**dict(w)) for w in WORKLOAD]
    streams = [r.result(timeout=300) for r in reqs]
    dt = time.perf_counter() - t0
    toks = sum(len(s) for s in streams)
    return streams, toks / dt


def _run_arm(submit, k_windows=5):
    """Median tokens/s over independent windows, after ONE untimed
    warm pass (`warmup()` compiles the greedy path; the sampled
    methods trace on first use, and that must not land inside a timed
    window). Window 0's streams ride along for the cross-arm identity
    verdict: both arms advance their admission counters 24 ids per
    pass, so window 0 spans ids [24, 48) in each — directly comparable
    even for sampled streams."""
    _serve_mix(submit)
    state = {"streams": None}

    def window(i):
        streams, rate = _serve_mix(submit)
        if i == 0:
            state["streams"] = streams
        return rate

    rate, vals, spread = _median_of_windows(window, k=k_windows)
    return {"rate": rate, "windows": [round(v, 1) for v in vals],
            "spread_pct": round(spread * 100, 1),
            "streams": state["streams"]}


def _time_to_healthy(router, k_windows=5):
    """Median ms from killing one idle replica to a fully-healthy
    roster again. The probe request lands on a survivor and kicks the
    background reviver; the replacement must warm from the shared disk
    store with zero live compiles."""
    zero_compile = [True]

    def window(i):
        victim = router._replicas[1 + i % (REPLICAS - 1)]
        victim.server._die(RuntimeError("bench kill"))
        t0 = time.perf_counter()
        router.submit(**dict(WORKLOAD[0])).result(timeout=60)
        deadline = t0 + 60
        while time.perf_counter() < deadline:
            if all(r["health"] == "healthy"
                   for r in router.status()["replicas"]):
                break
            time.sleep(0.002)
        dt_ms = (time.perf_counter() - t0) * 1e3
        assert all(r["health"] == "healthy"
                   for r in router.status()["replicas"]), \
            "roster never returned to healthy"
        if victim.server._store.stats["compiles"] != 0:
            zero_compile[0] = False
        return dt_ms

    ms, vals, spread = _median_of_windows(window, k=k_windows)
    return {"median_ms": round(ms, 1),
            "windows_ms": [round(v, 1) for v in vals],
            "spread_pct": round(spread * 100, 1),
            "kills": len(vals), "zero_compile": zero_compile[0]}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_FLEET_fresh.json")
    ap.add_argument("--windows", type=int, default=5)
    args = ap.parse_args(argv)

    net = _build_net()
    cache_dir = tempfile.mkdtemp(prefix="bench-fleet-exec-")

    print(f"# single arm: 1 server x {SINGLE_SLOTS} slots")
    single_srv = _server(net, cache_dir, SINGLE_SLOTS)
    single_srv.warmup()
    try:
        single = _run_arm(single_srv.submit, k_windows=args.windows)
    finally:
        single_srv.shutdown()
    print(f"# single: {single['rate']:.1f} tok/s "
          f"(spread {single['spread_pct']}%)")

    print(f"# fleet arm: {REPLICAS} replicas x {REPLICA_SLOTS} slots")
    router = FleetRouter(
        factory=lambda i: _server(net, cache_dir, REPLICA_SLOTS),
        num_replicas=REPLICAS, restart_budget=12)
    warm = router.warmup()
    try:
        fleet = _run_arm(router.submit, k_windows=args.windows)
        print(f"# fleet: {fleet['rate']:.1f} tok/s "
              f"(spread {fleet['spread_pct']}%)")
        healthy = _time_to_healthy(router, k_windows=args.windows)
        replacements = router.status()["replacements"]
    finally:
        router.shutdown()
    print(f"# time-to-healthy: {healthy['median_ms']} ms median over "
          f"{healthy['kills']} kills")

    identical = single["streams"] == fleet["streams"]
    assert identical, "fleet streams diverged from the bare server"
    assert healthy["zero_compile"], \
        "a replacement replica compiled live instead of warming " \
        "from the shared disk store"
    value = round(fleet["rate"] / single["rate"], 3)
    # single-core host: the three replicas time-share one core, so no
    # parallel speedup exists to claim — the ratio guards ROUTER
    # OVERHEAD, and falling far below 1.0 means the
    # relay/health/dispatch path regressed catastrophically
    assert value >= 0.5, f"fleet routing overhead ratio {value}"
    assert healthy["median_ms"] < 10_000, healthy

    doc = {
        "model": f"lstm_h{HIDDEN}_v{VOCAB}",
        "requests": N_REQUESTS,
        "single": {"slots": SINGLE_SLOTS,
                   "tok_per_s": round(single["rate"], 1),
                   "windows": single["windows"],
                   "spread_pct": single["spread_pct"]},
        "fleet": {"replicas": REPLICAS, "slots": REPLICA_SLOTS,
                  "tok_per_s": round(fleet["rate"], 1),
                  "windows": fleet["windows"],
                  "spread_pct": fleet["spread_pct"],
                  "warmup": warm,
                  "replacements": replacements},
        "time_to_healthy": healthy,
        "token_identity": {"requests": N_REQUESTS,
                           "identical": identical},
        "value": value,
        "metric": "fleet_3_replicas_vs_1_aggregate_tok_per_s",
        "unit": "x",
        "provenance": {"host": "cpu-1core", "jax": jax.__version__,
                       "windows": args.windows},
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"# headline: {value}x aggregate tok/s at equal slots, "
          f"{healthy['median_ms']} ms to healthy -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
